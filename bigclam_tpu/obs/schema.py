"""The events.jsonl schema: one flat JSON object per line, validated by a
dependency-free checker (no jsonschema in the container; the rules below ARE
the schema, shared by scripts/telemetry_smoke.py, `cli report`, and the
tier-1 test).

Base fields on EVERY event:

    v          int     schema version (SCHEMA_VERSION)
    run        str     run id — one RunTelemetry instance = one run
    pid        int     jax process index (0 before/without jax.distributed)
    t          float   seconds since the RunTelemetry was created
                       (kept from v1; same clock as elapsed_s)
    ts         float   wall-clock unix time (time.time) — for correlating
                       with external logs ONLY; never compute durations
                       from it (NTP steps / clock jumps corrupt them)
    elapsed_s  float   MONOTONIC seconds since the RunTelemetry was
                       created (time.perf_counter) — the ordering and
                       duration field consumers must use (obs.report does)
    kind       str     one of EVENT_KINDS

Kind-specific REQUIRED fields are listed in EVENT_KINDS; extra fields are
always allowed (events stay extensible without a schema bump — consumers
must ignore unknown keys). Unknown kinds are invalid: the smoke gate exists
to catch a producer drifting from this file.
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

# v2 (ISSUE 6): base fields ts + elapsed_s on every event; new `span` kind
SCHEMA_VERSION = 2

_NUM = (int, float)

# kind -> {required_field: allowed types}
EVENT_KINDS = {
    "start": {"entry": (str,)},            # run began (entry = fit/sweep/...)
    "end": {"wall_s": _NUM},               # run finalized
    "step": {"iter": (int,), "llh": _NUM},  # one optimizer iteration
    "metric": {},                          # non-step MetricsLogger record
    "stage": {"name": (str,), "seconds": _NUM},   # stage completed
    "memory": {"tag": (str,), "devices": (list,)},  # device-mem watermark
    "checkpoint": {"step": (int,)},        # checkpoint saved
    "restore": {"step": (int,)},           # checkpoint restored
    "compile": {"name": (str,), "seconds": _NUM},  # backend compile observed
    "model_build": {"model": (str,), "path": (str,)},  # trainer compiled
    "distributed_init": {"processes": (int,)},
    "cycle": {"cycle": (int,), "llh": _NUM},   # quality annealing cycle
    "stall": {"silent_s": _NUM, "rss_bytes": (int,)},  # heartbeat deadline hit
    "stall_escalated": {"stalls": (int,)},  # N consecutive stalls: watchdog
                                            # escalated (obs.heartbeat)
    "nonfinite": {"iter": (int,)},         # non-finite LLH sentinel fired
    "ingest": {"edges": (int,)},           # graph cache compiled
    "graph_load": {"source": (str,)},      # graph materialized on host
    "note": {},                            # freeform annotation
    # --- resilience (bigclam_tpu/resilience, ISSUE 5) ---
    "fault_injected": {"site": (str,), "fault": (str,)},  # harness fired
    "retry": {"site": (str,), "attempt": (int,)},   # transient failure,
                                                    # backing off
    "recovered": {"site": (str,), "attempts": (int,)},  # retry succeeded
    "gave_up": {"site": (str,), "attempts": (int,)},    # budget exhausted
                                                    # (cli report exits 1)
    "rollback": {"iter": (int,), "rollbacks": (int,)},  # non-finite LLH:
                                                    # state rolled back to
                                                    # the last finite
                                                    # snapshot, step cut
    "quarantine": {"shard": (int,)},       # crc-failed shard moved aside
                                           # and rebuilt from source
    "resume": {"step": (int,)},            # --resume auto restored a run
    # --- tracing & perf ledger (obs.trace / obs.ledger, ISSUE 6) ---
    "span": {"name": (str,), "path": (str,), "seconds": _NUM},
    # one closed span: `path` is the slash-joined nesting
    # ("fit/fit_loop/dispatch"), `name` its last segment; per-iteration
    # spans aggregate into the run report instead of emitting (emit=False)
    # --- model-health diagnostics (ops.diagnostics / obs.health, ISSUE
    # 8). Only `iter` is REQUIRED on `health`: every other payload field
    # (llh, grad_norm, ...) is a float that can legitimately go non-
    # finite mid-blow-up, and strict-JSON serialization then stringifies
    # it ("inf"/"nan" — telemetry._finite_safe), which a numeric
    # requirement would reject exactly on the events this layer exists
    # to capture.
    "health": {"iter": (int,)},            # one device health-pack sample
    "anomaly": {"check": (str,), "iter": (int,)},  # detector fired
                                           # (divergence / plateau /
                                           # oscillation / dead_communities
                                           # / cap_pressure)
    "sparse_comm": {"comm_cap": (int,), "comm_mode": (str,)},
    # sparse-collective layout committed at model build (cap, static
    # sparse-vs-psum mode, the touched-count it was sized from); the
    # PER-STEP occupancy/fallback counters ride `health` events
    # --- collective-traffic accounting (obs.comms, ISSUE 10) ---
    "comms": {"site": (str,), "op": (str,), "bytes_per_step": _NUM},
    # one collective site of a just-built train step (static bytes/step
    # model; payload/count/participants/phase/axis ride as extra fields).
    # Re-emitted when the layout changes (sparse cap refinement) — the
    # run report keeps the LAST model per site
    "balance": {"what": (str,), "max": _NUM, "mean": _NUM},
    # per-shard work-balance snapshot at model build (shard edge counts,
    # tile-pad waste): max/mean/skew/cv + the arg-max shard. Crossing
    # the imbalance threshold additionally fires an `anomaly` event
    # (check="imbalance", iter=-1 — build-time, not an iteration)
    # --- membership serving (bigclam_tpu.serve, ISSUE 14) ---
    "serve": {"family": (str,), "batch": (int,), "seconds": _NUM},
    # one flushed request batch (family = sorted "|"-joined families in
    # the batch, per-family counts ride as n_<family> extras, `step` the
    # serving snapshot generation); per-request latencies aggregate into
    # the stats the entry stamps into `final` (serve_p99_s etc.) so the
    # perf ledger verdicts serving p99 like step time
    "snapshot_swap": {"step": (int,)},
    # a running server hot-swapped to a newly published snapshot
    # (utils.checkpoint publish/latest; `previous` = the old generation)
    # --- sharded serving fleet (serve.fleet / serve.router, ISSUE 18) ---
    "fleet_publish": {"step": (int,), "shards": (int,)},
    # one fleet generation published (per-shard archives + manifest,
    # utils.checkpoint.publish_fleet_next); `bytes` may ride as an extra
    "rollout": {"step": (int,)},
    # the router flipped the fleet-wide serving generation — only after
    # EVERY healthy replica of EVERY shard reported `step` loaded
    "route": {"queries": (int,), "shards": (int,)},
    # one routed query batch (FleetRouter.run_queries); aggregates land
    # in `final` under the same serve_* keys as `cli serve`, plus
    # serve_shards/serve_replicas/serve_shard_stats/mixed_generation
    # --- distributed query tracing + freshness (ISSUE 19) ---
    "qtrace": {"trace_id": (str,), "family": (str,), "total_s": _NUM},
    # one slow-query exemplar: the full cross-process trace of a routed
    # query — `hops` (list of per-sub-send dicts with shard / wire_s /
    # decode_s / queue_s / batch_wait_s / execute_s) and `merge_s`
    # (router-side time not spent on the wire) ride as extras. The
    # router keeps the top-N slowest traces per window (serve.router
    # TRACE_WINDOW/TRACE_TOP) so the log stays bounded under load
    "freshness": {"generation_age_s": _NUM},
    # serving staleness sample (ROADMAP 3a): wall-clock seconds since
    # the serving generation was published, emitted by the router at
    # refresh and at batch completion; `step` (the serving generation)
    # and `rollouts` ride as extras. Aggregates land in `final` as
    # generation_age_s, which the perf ledger VERDICTS
    # --- self-healing serving fleet (serve.supervise, ISSUE 20) ---
    "replica_restart": {"member": (str,), "shard": (int,)},
    # the supervisor is respawning a replica slot after an unplanned
    # exit (restart-on-exit with RetryPolicy backoff); `restarts` (the
    # slot's lifetime respawn count) rides as an extra
    "replica_quarantined": {"member": (str,), "shard": (int,)},
    # crash-loop detection fired: more than quarantine_after consecutive
    # failures parked the slot — the fleet degrades to its surviving
    # replicas instead of burning CPU on a doomed respawn loop
    # (`failures` rides as an extra)
    "membership": {"seq": (int,), "members": (int,)},
    # the membership document changed: the supervisor published seq N
    # with `members` live entries (`roster` — id/shard/state/restarts
    # per member — rides as an extra on supervisor-emitted events), or
    # the router reconciled its endpoint set against it
    # --- incremental graph deltas (ISSUE 15) ---
    "delta_ingest": {"edges_added": (int,), "touched_shards": (int,)},
    # one applied edge delta (GraphStore.apply_delta): directed edges
    # added, how many shard ranges were rebuilt (touched_frac /
    # delta_seq / phi_rebaked ride as extras). Untouched shard blobs
    # are byte-identical by contract
    "refit": {"touched": (int,), "rounds": (int,)},
    # one warm-start incremental refit (models.refit.warm_start_refit):
    # delta-touched rows, block-coordinate rounds run; refit_nodes /
    # touched_frac / escalated / converged / foldin_iters ride as
    # extras. An escalation additionally fires `anomaly` events
    # (source="refit") carrying the detector findings
    # --- memory accounting (obs.memory, ISSUE 12) ---
    "memory_model": {"buffer": (str,), "bytes": _NUM},
    # one buffer of a trainer's static memory model, baked at step
    # build: scope="device" rows are per-device HBM (category state /
    # graph / scratch / transient / collective), scope="host" rows are
    # the per-stage host-RSS model (stage + dominant flag). Re-emitted
    # models REPLACE their site set via reset_model on the batch's
    # first event, exactly like `comms`. Live-vs-model drift past the
    # band fires an `anomaly` event (check="memory_drift", iter=-1)
}

_BASE = {
    "v": (int,), "run": (str,), "pid": (int,), "t": _NUM,
    "ts": _NUM, "elapsed_s": _NUM, "kind": (str,),
}


def validate_event(event) -> List[str]:
    """Schema errors for one decoded event dict; [] when valid."""
    errors: List[str] = []
    if not isinstance(event, dict):
        return [f"event is {type(event).__name__}, not an object"]
    for field, types in _BASE.items():
        if field not in event:
            errors.append(f"missing base field {field!r}")
        elif not isinstance(event[field], types) or isinstance(
            event[field], bool
        ):
            errors.append(
                f"{field!r} is {type(event[field]).__name__}, "
                f"want {'/'.join(t.__name__ for t in types)}"
            )
    if errors:
        return errors
    if event["v"] != SCHEMA_VERSION:
        errors.append(f"schema version {event['v']} != {SCHEMA_VERSION}")
    kind = event["kind"]
    required = EVENT_KINDS.get(kind)
    if required is None:
        return errors + [f"unknown kind {kind!r}"]
    for field, types in required.items():
        if field not in event:
            errors.append(f"kind {kind!r} missing field {field!r}")
        elif not isinstance(event[field], types) or isinstance(
            event[field], bool
        ):
            errors.append(
                f"{kind}.{field} is {type(event[field]).__name__}, "
                f"want {'/'.join(t.__name__ for t in types)}"
            )
    return errors


def validate_events_file(path: str) -> Tuple[int, List[str]]:
    """(number of events, errors) for a whole events.jsonl; every line must
    parse as JSON and validate. Error strings carry 1-based line numbers."""
    import json

    n = 0
    errors: List[str] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            n += 1
            try:
                event = json.loads(line)
            except ValueError as e:
                errors.append(f"line {lineno}: not JSON ({e})")
                continue
            errors.extend(
                f"line {lineno}: {msg}" for msg in validate_event(event)
            )
    return n, errors


def summarize_kinds(events: Iterable[dict]) -> dict:
    """{kind: count} over decoded events (report + rendering helper)."""
    out: dict = {}
    for e in events:
        k = e.get("kind", "?")
        out[k] = out.get(k, 0) + 1
    return out
