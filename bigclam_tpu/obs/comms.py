"""Collective-traffic accounting + host-skew observability (ISSUE 10).

The span layer (obs.trace) says where wall-clock went and the health pack
(obs.health) says what the optimizer is doing — but nothing said what the
INTERCONNECT is doing: every `psum`/`ppermute`/`all_gather` site in
`parallel/` moved unmeasured bytes, and a slow host was invisible until
the whole fit was slow. For power-law graph clustering the comm volume
and its per-participant skew, not FLOPs, decide scaling (Sparse
Allreduce, arXiv:1312.3020; pre-exascale MCL, arXiv:2002.10083) — this
module makes both first-class, gateable run signals.

Three layers, all jax-free at import (`cli report`/`cli watch` run on
data-prep hosts):

* **Static bytes-per-step model.** Each sharded trainer family bakes a
  `CommsModel` at step-build time: one `Site` per collective site of its
  compiled step (site id -> op kind, payload bytes, occurrences/step,
  participants, phase, mesh axis), built by the `*_step_model` functions
  here from the SAME shape arithmetic the trainer committed
  (n_pad/k_pad/dp/tp, the sparse cap + static mode). Emitted as `comms`
  schema events (one per site), summed into the run report and the perf
  ledger (`comms_bytes_per_step`, verdicted by `cli perf diff`).

  Wire-byte conventions (documented here once, shared by model and
  reconciliation): an `all_gather` of a local s-byte shard over p
  participants receives (p-1)*s bytes per device; a `psum` of an s-byte
  array moves 2*s*(p-1)/p per device (ring allreduce: reduce-scatter +
  all-gather); a `ppermute` hop moves s bytes per device; `pmax` follows
  the psum formula. Axes of size 1 contribute zero (the collective
  compiles to identity).

* **Reconciliation.** `CommsModel.remeasure(payloads)` replaces modeled
  site payloads with MEASURED ones — the actual addressable-shard nbytes
  of the live TrainState buffers (`measured_payloads`), and the sparse
  trainers' runtime exchanged-ids/dense-fallback counters — so the gate
  (scripts/comms_gate.py) can assert the static model agrees with what
  the step actually places on the wire, per family, across dp. A padding
  or layout change that silently inflates traffic now fails a gate
  instead of landing as folklore.

* **Balance + straggler detection.** `balance_stats` turns per-shard
  edge counts (from the store manifest or the CSR bounds) and tile-pad
  waste into skew figures emitted as `balance` events;
  `emit_imbalance_anomaly` turns the old `_warn_imbalance_counts` stderr
  lines into `anomaly` events (check="imbalance") that `cli report`,
  `cli watch`, and the heartbeat stall context all render.
  `detect_host_skew` is a PURE detector (the PR 8 anomaly machinery's
  report-time analog) over the merged per-process run reports: a host
  everyone waits on shows up as the MINIMUM per-pid sync-span total
  while its peers' sync balloons (the waiters rule), and a host burning
  time OUTSIDE the loop phases (GC, a planted delay, a slow NIC driver)
  shows up as loop-overhead skew (the overhead rule). Both fire one
  finding naming the offending pid + host. Single-process fake-host
  runs (two per-pid reports synthesized into one telemetry dir) exercise
  the detector end to end without a process group — the tier-1 path on
  jax 0.4.37, where the 2-proc worker modes skip.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

_NUM = (int, float)

# bucket/shard skew past this multiple of the mean marks the layout as
# imbalance-anomalous (shared with parallel.ring's warning heuristic —
# RING_IMBALANCE_FACTOR aliases this so the anomaly fires exactly where
# the stderr warning used to)
IMBALANCE_FACTOR = 4.0

# report-time host-skew detector thresholds (detect_host_skew); host-side
# knobs like obs.health.DEFAULTS — deliberately NOT config fields
DEFAULTS: Dict[str, float] = {
    "straggler_factor": 3.0,    # max/min skew of the per-pid signal
    "straggler_floor_s": 0.05,  # absolute seconds floor (noise guard)
}


def wire_bytes(op: str, payload_bytes: float, participants: int) -> float:
    """Per-device wire bytes of ONE occurrence of a collective moving a
    `payload_bytes` local array over `participants` (see module
    docstring for the conventions). Size-1 axes cost nothing.

    The 2D partition (ISSUE 16) adds three single-pass ops: a
    `reduce_scatter`/`psum_scatter` of an s-byte local array sends
    s*(p-1)/p (each device keeps its own 1/p slice — half a ring
    allreduce), and an `all_to_all` of an s-byte local buffer likewise
    moves s*(p-1)/p (the self slice never touches the wire)."""
    p = max(int(participants), 1)
    if p <= 1:
        return 0.0
    if op == "all_gather":
        return float(payload_bytes) * (p - 1)
    if op == "ppermute":
        return float(payload_bytes)
    if op in ("psum", "pmax", "pmin"):
        return 2.0 * float(payload_bytes) * (p - 1) / p
    if op in ("reduce_scatter", "psum_scatter", "all_to_all"):
        return float(payload_bytes) * (p - 1) / p
    raise ValueError(f"unknown collective op {op!r}")


@dataclasses.dataclass(frozen=True)
class Site:
    """One collective site of a compiled train step.

    payload_bytes: the LOCAL array bytes one occurrence moves (per
    participant, pre-convention); count: occurrences per optimizer step
    (fractional = cadence-gated, e.g. 1/health_every); phase: which part
    of the step issues it (gather / reduce / rotate / support /
    exchange / health)."""

    site: str
    op: str
    payload_bytes: float
    count: float
    participants: int
    phase: str
    axis: str

    @property
    def bytes_per_step(self) -> float:
        return wire_bytes(self.op, self.payload_bytes, self.participants) \
            * self.count

    def to_fields(self) -> Dict[str, Any]:
        return {
            "site": self.site,
            "op": self.op,
            "bytes_per_step": round(self.bytes_per_step, 1),
            "payload_bytes": round(float(self.payload_bytes), 1),
            "count": round(float(self.count), 4),
            "participants": int(self.participants),
            "phase": self.phase,
            "axis": self.axis,
        }


@dataclasses.dataclass(frozen=True)
class CommsModel:
    """The static bytes-per-step model one trainer baked at step build."""

    family: str                  # sharded | ring | sparse
    model: str                   # trainer class name
    sites: Tuple[Site, ...]
    params: Dict[str, Any]       # the shape arithmetic inputs, for the

    def bytes_per_step(self) -> float:          # artifact/report record
        return sum(s.bytes_per_step for s in self.sites)

    def site_bytes(self) -> Dict[str, float]:
        return {s.site: round(s.bytes_per_step, 1) for s in self.sites}

    def remeasure(self, payloads: Dict[str, float]) -> "CommsModel":
        """A copy with the named sites' payloads replaced by MEASURED
        bytes (live buffer nbytes / runtime counters); unnamed sites keep
        their modeled payloads. The gate compares bytes_per_step() of
        the pair — drift means the model no longer describes the step."""
        sites = tuple(
            dataclasses.replace(
                s, payload_bytes=float(payloads[s.site])
            )
            if s.site in payloads
            else s
            for s in self.sites
        )
        return dataclasses.replace(self, sites=sites)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "family": self.family,
            "model": self.model,
            "bytes_per_step": round(self.bytes_per_step(), 1),
            "sites": [s.to_fields() for s in self.sites],
            "params": dict(self.params),
        }


def _scalar_payload(itemsize: int, num_candidates: int) -> float:
    """The per-step scalar-reduce bundle every family shares: the psum'd
    global LLH plus the (num_candidates + 1,) int32 accept histogram."""
    return itemsize + (num_candidates + 1) * 4


def sharded_step_model(
    n_pad: int,
    k_pad: int,
    dp: int,
    tp: int,
    itemsize: int,
    num_candidates: int,
    edge_slots: int = 0,
    health_every: int = 0,
    model: str = "ShardedBigClamModel",
    health_participants: Optional[int] = None,
) -> CommsModel:
    """Collective sites of the all-gather sharded step (parallel/sharded
    .py, XLA and CSR schedules — same collectives at tp == 1; tp > 1
    adds the per-edge partial-dot psums over "k"). `edge_slots` is the
    PER-SHARD padded edge-slot count (only the tp > 1 sites read it).
    `health_participants` is the device count of the health-pack psums —
    they run OUTSIDE shard_map on the global arrays, so the reduction
    spans the whole mesh (dp*tp), not just the node axis; None keeps the
    historical dp default for callers that never shard "k"."""
    n_loc = n_pad // max(dp, 1)
    k_loc = k_pad // max(tp, 1)
    sites = [
        Site("sharded/all_gather_F", "all_gather",
             n_loc * k_loc * itemsize, 1, dp, "gather", "nodes"),
        # sumF at the top of the step + sumF_new after the update
        Site("sharded/psum_sumF", "psum",
             k_loc * itemsize, 2, dp, "reduce", "nodes"),
        Site("sharded/psum_scalars", "psum",
             _scalar_payload(itemsize, num_candidates), 1, dp,
             "reduce", "nodes"),
    ]
    if tp > 1:
        # per-edge partial dots completed over "k": one grad sweep + one
        # per Armijo candidate, each psum'ing every padded edge slot once
        sites.append(Site(
            "sharded/psum_edge_dots", "psum",
            edge_slots * itemsize, 1 + num_candidates, tp, "reduce", "k",
        ))
        # rowdot psums of (n_loc,): gg + the two node-tail terms, plus
        # two per candidate tail (armijo_tail_select_sharded)
        sites.append(Site(
            "sharded/psum_rowdots", "psum",
            n_loc * itemsize, 3 + 2 * num_candidates, tp, "reduce", "k",
        ))
    if health_every and health_every > 0:
        sites.append(Site(
            "sharded/psum_health", "psum", 3 * 4, 1.0 / health_every,
            int(health_participants or dp), "health", "mesh",
        ))
    return CommsModel(
        family="sharded", model=model, sites=tuple(sites),
        params={"n_pad": n_pad, "k_pad": k_pad, "dp": dp, "tp": tp,
                "itemsize": itemsize, "edge_slots": edge_slots},
    )


def ring_step_model(
    n_pad: int,
    k_pad: int,
    dp: int,
    tp: int,
    itemsize: int,
    num_candidates: int,
    bucket_slots: int = 0,
    health_every: int = 0,
    model: str = "RingBigClamModel",
    health_participants: Optional[int] = None,
) -> CommsModel:
    """Collective sites of the ring-pass step (parallel/ring.py): the
    F-shard rotation replaces the all-gather — two full rotations per
    step (gradient pass + candidate pass), dp ppermute hops each
    (rotate_scan scans dp phases, one hop per phase, so every device
    also re-receives its own shard on the closing hop), every hop
    moving one (n_loc, k_loc) shard. Per pass that is dp*shard on the
    wire vs the all-gather's (dp-1)*shard — a dp/(dp-1) premium, and
    the candidate pass re-rotates where the all-gather step reuses its
    one gathered copy, so the ring's modeled bytes/step are HIGHER; its
    win is the O(2 shards) peak HBM, which is exactly why bytes/step
    accounting, not memory, is the honest axis for comparing the
    schedules. `bucket_slots` is the per-(shard, phase) padded
    edge-slot count (tp > 1 sites only)."""
    n_loc = n_pad // max(dp, 1)
    k_loc = k_pad // max(tp, 1)
    sites = [
        Site("ring/ppermute_F_rot", "ppermute",
             n_loc * k_loc * itemsize, 2 * dp if dp > 1 else 0, dp,
             "rotate", "nodes"),
        Site("ring/psum_sumF", "psum",
             k_loc * itemsize, 2, dp, "reduce", "nodes"),
        Site("ring/psum_scalars", "psum",
             _scalar_payload(itemsize, num_candidates), 1, dp,
             "reduce", "nodes"),
    ]
    if tp > 1:
        sites.append(Site(
            "ring/psum_edge_dots", "psum",
            bucket_slots * itemsize, (1 + num_candidates) * dp, tp,
            "reduce", "k",
        ))
        sites.append(Site(
            "ring/psum_rowdots", "psum",
            n_loc * itemsize, 3 + 2 * num_candidates, tp, "reduce", "k",
        ))
    if health_every and health_every > 0:
        sites.append(Site(
            "ring/psum_health", "psum", 3 * 4, 1.0 / health_every,
            int(health_participants or dp), "health", "mesh",
        ))
    return CommsModel(
        family="ring", model=model, sites=tuple(sites),
        params={"n_pad": n_pad, "k_pad": k_pad, "dp": dp, "tp": tp,
                "itemsize": itemsize, "bucket_slots": bucket_slots},
    )


def sparse_step_model(
    n_pad: int,
    m: int,
    k_pad: int,
    dp: int,
    itemsize: int,
    num_candidates: int,
    cap: int,
    mode: str,
    support_every: int = 1,
    health_every: int = 0,
    model: str = "SparseShardedBigClamModel",
    health_participants: Optional[int] = None,
) -> CommsModel:
    """Collective sites of the sparse-representation sharded step
    (parallel/sparse_sharded.py + sparse_collectives.py). The member
    exchange scales with M, not K; the sumF allreduce moves fixed
    (cap,) id/value buffers in 'sparse' mode (the wire cost is the CAP,
    not the touched count — occupancy below cap is headroom, not saved
    bytes) and the dense (k_pad,) psum in 'dense' mode."""
    from bigclam_tpu.parallel.sparse_collectives import (
        exchange_payload_bytes,
    )

    n_loc = n_pad // max(dp, 1)
    row_bytes = m * (4 + itemsize)          # int32 id + weight per slot
    sup = max(int(support_every), 1)
    sites = [
        # the post-support id/weight gather pair feeds grad + candidates
        # every step; the support pass gathers a second pair on cadence
        Site("sparse/all_gather_members", "all_gather",
             n_loc * row_bytes, 1.0 + 1.0 / sup, dp, "gather", "nodes"),
        Site("sparse/psum_scalars", "psum",
             _scalar_payload(itemsize, num_candidates), 1, dp,
             "reduce", "nodes"),
    ]
    if mode == "sparse":
        sites.append(Site(
            "sparse/allreduce_touched", "all_gather",
            exchange_payload_bytes(cap, itemsize), 2, dp,
            "exchange", "nodes",
        ))
        sites.append(Site(
            "sparse/pmax_touched_count", "pmax", 4, 2, dp,
            "exchange", "nodes",
        ))
    else:
        sites.append(Site(
            "sparse/psum_sumF", "psum", k_pad * itemsize, 2, dp,
            "reduce", "nodes",
        ))
        sites.append(Site(
            "sparse/pmax_touched_count", "pmax", 4, 2, dp,
            "exchange", "nodes",
        ))
    if health_every and health_every > 0:
        # support-churn psum runs every step when health is on (the
        # latch needs it); grad stats ride the cadence
        hp = int(health_participants or dp)
        sites.append(Site(
            "sparse/psum_health", "psum", 4, 1, hp, "health", "mesh",
        ))
        sites.append(Site(
            "sparse/psum_grad_stats", "psum", 3 * 4,
            1.0 / max(int(health_every), 1), hp, "health", "mesh",
        ))
    return CommsModel(
        family="sparse", model=model, sites=tuple(sites),
        params={"n_pad": n_pad, "m": m, "k_pad": k_pad, "dp": dp,
                "itemsize": itemsize, "cap": cap, "mode": mode,
                "support_every": sup},
    )


def twod_step_model(
    n_pad: int,
    k_pad: int,
    rows: int,
    cols: int,
    itemsize: int,
    num_candidates: int,
    edge_slots: int = 0,
    closure_cap: int = 1,
    health_every: int = 0,
    model: str = "TwoDShardedBigClamModel",
    row_bytes: Optional[float] = None,
    grad_exchange: str = "dense",
    grad_cap: int = 0,
    fused: bool = False,
) -> CommsModel:
    """Collective sites of the 2D edge-block step (parallel/twod.py).
    `row_bytes` overrides the per-row wire width of the F gather and
    the closure exchange (default k_pad * itemsize) — the sparse
    preflight prices its m ids+weights member rows through the same
    schedule.

    The communication-avoiding trade against the 1D all-gather, per
    device per step: the dense (n_pad/p)*k_pad gather shrinks by the
    row-group factor (participants cols, not p) and the rest of F moves
    only as the CAPPED closure all_to_all over rows — closure_cap rows
    per peer group instead of whole blocks. The price is the cols grad
    reduction plus the candidate/LLH psum_scatters over cols (zero at
    cols == 1), which is why `cli preflight` prices both layouts
    instead of assuming 2d wins everywhere.

    The grad reduction is grad_exchange-baked (ISSUE 17):
    "dense" is the PR 16 full row-band psum; "closure" replaces it with
    the two-phase touched-rows all_to_all over the baked pair lists —
    2 * cols * grad_cap rows on the wire instead of the n_row band, a
    strict win whenever grad_cap < n_blk. grad_cap == 0 under "closure"
    means no block pair touched any row: the exchange is skipped at
    trace time, priced 0 bytes. `fused` (kernel_path csr_fused_2d[_kb])
    changes compute, not collectives — recorded in params for the
    ledger, no site changes."""
    p = max(rows * cols, 1)
    n_blk = n_pad // p
    n_row = cols * n_blk
    rb = float(row_bytes) if row_bytes else float(k_pad * itemsize)
    sites = [
        # processor row's src rows: 1/rows of the 1D dense gather
        Site("twod/allgather_srcF", "all_gather",
             n_blk * rb, 1, cols, "gather", "cols"),
        # capped closure exchange: the (rows, cap, k) send buffer, self
        # slice never on the wire
        Site("twod/alltoall_closure", "all_to_all",
             rows * closure_cap * rb, 1, rows,
             "exchange", "rows"),
    ]
    if grad_exchange == "closure":
        if grad_cap > 0:
            # touched-rows grad exchange: phase A routes the (cols,
            # grad_cap, k) partial-row buffer to the owner columns,
            # phase B routes the complete sums back — count 2
            sites.append(Site(
                "twod/alltoall_grad_closure", "all_to_all",
                cols * grad_cap * k_pad * itemsize, 2, cols,
                "exchange", "cols",
            ))
            # the capped-exchange count pmax over cols + the counter
            # replication over rows (comm_ids / comm_dense)
            sites.append(Site(
                "twod/pmax_grad_count", "pmax", 4, 1, cols,
                "exchange", "cols",
            ))
            sites.append(Site(
                "twod/pmax_grad_count_rows", "pmax", 4, 2, rows,
                "exchange", "rows",
            ))
        # grad_cap == 0: every partial is exactly 0.0 — no exchange
    else:
        # row-group gradient completion (full psum: the candidate pass
        # re-reads grad at every group src row)
        sites.append(Site(
            "twod/psum_grad", "psum",
            n_row * k_pad * itemsize, 1, cols, "reduce", "cols",
        ))
    sites += [
        # tentpole (c): candidate/LLH accumulators reduced AND scattered
        # in one pass — each chip keeps only its own block's columns
        Site("twod/psum_scatter_cand", "psum_scatter",
             num_candidates * n_row * itemsize, 1, cols,
             "reduce", "cols"),
        Site("twod/psum_scatter_nbr_llh", "psum_scatter",
             n_row * itemsize, 1, cols, "reduce", "cols"),
        Site("twod/psum_sumF", "psum",
             k_pad * itemsize, 2, p, "reduce", "mesh"),
        Site("twod/psum_scalars", "psum",
             _scalar_payload(itemsize, num_candidates), 1, p,
             "reduce", "mesh"),
    ]
    if health_every and health_every > 0:
        sites.append(Site(
            "twod/psum_health", "psum", 3 * 4, 1.0 / health_every,
            p, "health", "mesh",
        ))
    return CommsModel(
        family="twod", model=model, sites=tuple(sites),
        params={"n_pad": n_pad, "k_pad": k_pad, "rows": rows,
                "cols": cols, "itemsize": itemsize,
                "edge_slots": edge_slots, "closure_cap": closure_cap,
                "grad_exchange": grad_exchange, "grad_cap": grad_cap,
                "fused": bool(fused)},
    )


def twod_measured(model: CommsModel, state) -> CommsModel:
    """Remeasured 2D model from a live TrainState: the dense payloads
    from the state buffers (measured_payloads), plus — when the closure
    grad exchange is engaged — the runtime counters' verdict on the
    exchange site: while the sparse branch holds, the wire stays
    cap-sized (the modeled payload IS the measured one — occupancy
    below cap is headroom, same convention as the sparse-allreduce
    exchange), but a step whose dense-fallback counter fired moved the
    full row-band psum through that site, so the site is swapped for
    its dense twin before bytes_per_step comparison."""
    m = model.remeasure(measured_payloads("twod", state))
    gcap = int(model.params.get("grad_cap", 0) or 0)
    if (
        model.params.get("grad_exchange") != "closure"
        or gcap <= 0
        or getattr(state, "comm_ids", None) is None
        or not bool(int(state.comm_dense))
    ):
        return m
    rows = int(model.params.get("rows", 1))
    cols = int(model.params.get("cols", 1))
    n_pad = int(model.params.get("n_pad", 0))
    k_pad = int(model.params.get("k_pad", 0))
    itemsize = int(model.params.get("itemsize", 4))
    n_row = cols * (n_pad // max(rows * cols, 1))
    dense = Site(
        "twod/alltoall_grad_closure", "psum",
        n_row * k_pad * itemsize, 1, cols, "exchange", "cols",
    )
    sites = tuple(
        dense if s.site == "twod/alltoall_grad_closure" else s
        for s in m.sites
    )
    return dataclasses.replace(m, sites=sites)


# --------------------------------------------------------- reconciliation
def _shard_nbytes(arr) -> Optional[float]:
    """Bytes of this process's first addressable shard of a (possibly
    globally sharded) jax.Array — the per-participant payload the step
    actually places. None when the array exposes no shard API (plain
    numpy in tests)."""
    shards = getattr(arr, "addressable_shards", None)
    if not shards:
        nbytes = getattr(arr, "nbytes", None)
        return float(nbytes) if nbytes is not None else None
    return float(shards[0].data.nbytes)


def measured_payloads(family: str, state) -> Dict[str, float]:
    """Measured per-site payload bytes from a live TrainState's device
    buffers (see CommsModel.remeasure). Dense families only — the sparse
    trainer's runtime counters go through sparse_measured."""
    out: Dict[str, float] = {}
    f = _shard_nbytes(state.F)
    s = _shard_nbytes(state.sumF)
    if family == "sharded":
        if f is not None:
            out["sharded/all_gather_F"] = f
        if s is not None:
            out["sharded/psum_sumF"] = s
    elif family == "ring":
        if f is not None:
            out["ring/ppermute_F_rot"] = f
        if s is not None:
            out["ring/psum_sumF"] = s
    elif family == "twod":
        # the F block IS the all_gather payload (and the per-row unit of
        # the closure exchange); the closure send buffer itself is a step
        # transient, not state — it stays modeled
        if f is not None:
            out["twod/allgather_srcF"] = f
        if s is not None:
            out["twod/psum_sumF"] = s
    return out


def sparse_measured(model: CommsModel, state) -> Dict[str, Any]:
    """Reconcile the sparse model against the RUNTIME exchange counters
    riding the state (comm_ids = max touched ids over shards, comm_dense
    = a dense-psum fallback fired): the wire stays cap-sized while the
    sparse branch holds, so the checks are occupancy (exchanged <= cap)
    and the fallback flipping the accounting to the dense psum."""
    from bigclam_tpu.parallel.sparse_collectives import (
        exchange_payload_bytes,
    )

    cap = int(model.params.get("cap", 0))
    itemsize = int(model.params.get("itemsize", 4))
    k_pad = int(model.params.get("k_pad", 0))
    dp = int(model.params.get("dp", 1))
    exchanged = int(state.comm_ids)
    fell_back = bool(int(state.comm_dense))
    ids_row = _shard_nbytes(state.ids)
    w_row = _shard_nbytes(state.F)
    payloads: Dict[str, float] = {}
    if ids_row is not None and w_row is not None:
        payloads["sparse/all_gather_members"] = ids_row + w_row
    if fell_back:
        # that step's exchange was the dense psum — measured wire for the
        # allreduce site is the dense formula, not the capped buffers
        measured_exchange = 2 * wire_bytes("psum", k_pad * itemsize, dp)
    else:
        measured_exchange = 2 * wire_bytes(
            "all_gather", exchange_payload_bytes(cap, itemsize), dp
        )
    return {
        "payloads": payloads,
        "exchanged_ids": exchanged,
        "dense_fallback": fell_back,
        "cap": cap,
        "occupancy": exchanged / max(cap, 1),
        "exchange_bytes_per_step": round(measured_exchange, 1),
    }


# ------------------------------------------------------ balance / skew
def balance_stats(counts: Sequence[float]) -> Dict[str, Any]:
    """Skew statistics over per-shard work counts (directed edges, tile
    slots): max, mean (floored at 1 like the ring heuristic), skew =
    max/mean, cv, and the arg-max shard — what the `balance` events and
    the imbalance anomaly carry."""
    vals = [float(v) for v in counts]
    if not vals:
        return {"max": 0.0, "mean": 1.0, "skew": 0.0, "cv": 0.0,
                "argmax": -1}
    mx = max(vals)
    mean = max(sum(vals) / len(vals), 1.0)
    var = sum((v - mean) ** 2 for v in vals) / len(vals)
    return {
        "max": mx,
        "mean": round(mean, 2),
        "skew": round(mx / mean, 3),
        "cv": round(math.sqrt(var) / mean, 4),
        "argmax": max(range(len(vals)), key=lambda i: vals[i]),
    }


def owner_pid(shard: int, num_shards: int, process_count: int) -> int:
    """Owning process of a store/trainer shard under the process-major
    contiguous layout (multihost.host_shard_ids): host h of H owns
    shards [h*S/H, (h+1)*S/H)."""
    pc = max(int(process_count), 1)
    s = max(int(num_shards), 1)
    return min(int(shard) * pc // s, pc - 1)


# ----------------------------------------------------------- emission
def emit_model(cm: CommsModel) -> None:
    """One `comms` event per collective site of a just-built step (plus
    the run-report/ledger accumulation RunTelemetry.event folds in).
    The FIRST event of the batch carries reset_model=True: a re-emitted
    model (the sparse cap refinement can flip the whole collective MODE)
    must REPLACE its previous site set in every consumer, or a stale
    site from the abandoned layout inflates bytes/step forever. No-op
    with telemetry off."""
    from bigclam_tpu.obs import telemetry as _obs

    tel = _obs.current()
    if tel is None:
        return
    for i, s in enumerate(cm.sites):
        tel.event("comms", model=cm.model, family=cm.family,
                  reset_model=1 if i == 0 else 0, **s.to_fields())


def emit_balance(what: str, stats: Dict[str, Any], **fields) -> None:
    """One `balance` event (shard edge-count skew, tile-pad waste). The
    skew itself is a finding for the report/watch; crossing
    IMBALANCE_FACTOR is the anomaly (emit_imbalance_anomaly)."""
    from bigclam_tpu.obs import telemetry as _obs

    tel = _obs.current()
    if tel is None:
        return
    payload = {k: v for k, v in stats.items()}
    payload.update(fields)
    tel.event("balance", what=what, **payload)


def emit_shard_balance(
    what: str,
    counts: Sequence[float],
    num_shards: int,
    process_count: int = 1,
    hint: str = "",
    **fields,
) -> Dict[str, Any]:
    """The one balance-emission path every sharded trainer build goes
    through: a `balance` event with the skew stats (+ any tile-pad-waste
    fields), and — past IMBALANCE_FACTOR — the imbalance anomaly naming
    the worst shard and its owning process. Returns the stats either
    way (telemetry off included) so callers can reuse them."""
    stats = balance_stats(counts)
    emit_balance(what, stats, **fields)
    if stats["skew"] > IMBALANCE_FACTOR:
        emit_imbalance_anomaly(
            what, stats["max"], stats["mean"],
            worst_shard=stats["argmax"],
            host=owner_pid(stats["argmax"], num_shards, process_count),
            hint=hint,
        )
    return stats


def emit_imbalance_anomaly(
    what: str,
    max_count: float,
    mean: float,
    worst_shard: Optional[int] = None,
    host: Optional[int] = None,
    hint: str = "",
) -> None:
    """The `_warn_imbalance_counts` stderr line as a first-class anomaly
    event (check="imbalance", build-time: iter=-1) naming the worst
    shard and — when ownership is known — the host that holds it, so the
    report, `cli watch`, and `cli perf diff`'s anomaly count all see
    what used to scroll past on stderr."""
    from bigclam_tpu.obs import telemetry as _obs

    tel = _obs.current()
    if tel is None:
        return
    fields: Dict[str, Any] = {
        "what": what,
        "max": float(max_count),
        "mean": round(float(mean), 2),
        "factor": round(float(max_count) / max(float(mean), 1e-9), 2),
    }
    if worst_shard is not None:
        fields["worst_shard"] = int(worst_shard)
    if host is not None:
        fields["host_pid"] = int(host)
    if hint:
        fields["hint"] = hint
    tel.event("anomaly", check="imbalance", iter=-1, **fields)


# --------------------------------------------- report-time skew detector
def _pid_of(report: Dict[str, Any]) -> str:
    return str(report.get("pid", "?"))


def _host_of(report: Dict[str, Any]) -> str:
    return str((report.get("fingerprint", {}) or {}).get("host", "?"))


def sync_seconds(report: Dict[str, Any]) -> float:
    """Total fit-loop sync-span seconds of one per-process report (the
    host block on the step's scalar LLH — device compute + in-step
    collective waits + D2H are indistinguishable from the host, so this
    IS the 'waiting on the gang' phase)."""
    spans = (report.get("spans", {}) or {}).get("seconds", {}) or {}
    return sum(
        float(v) for k, v in spans.items() if k.endswith("fit_loop/sync")
    )


def loop_overhead_seconds(report: Dict[str, Any]) -> float:
    """Seconds the fit stage spent OUTSIDE the per-iteration phase spans
    (dispatch/sync/callback/checkpoint/extract_F): host-side work the
    taxonomy does not attribute — GC, a slow filesystem, a planted
    per-host delay. The overhead rule of detect_host_skew keys on this
    because a straggler's slowness lives exactly here (its own sync is
    SHORT — everyone else waits on it)."""
    spans = (report.get("spans", {}) or {}).get("seconds", {}) or {}
    parents = {
        k.split("/fit_loop/")[0]
        for k in spans
        if "/fit_loop/" in k
    }
    if not parents and any(k.startswith("fit_loop/") for k in spans):
        parents = {""}
    total = 0.0
    for parent in parents:
        prefix = f"{parent}/fit_loop/" if parent else "fit_loop/"
        phase_sum = sum(
            float(v) for k, v in spans.items() if k.startswith(prefix)
        )
        parent_total = float(spans.get(parent, phase_sum)) if parent \
            else phase_sum
        total += max(parent_total - phase_sum, 0.0)
    return total


def detect_host_skew(
    reports: List[Dict[str, Any]],
    thresholds: Optional[Dict[str, float]] = None,
) -> List[Dict[str, Any]]:
    """Straggler findings over the merged per-process run reports (pure;
    deterministic thresholds — DEFAULTS). Two rules, at most one finding:

    * waiters: one pid's sync total is a straggler_factor below its
      peers' (they sat in the collective waiting on it) — fire naming
      the MINIMUM-sync pid.
    * overhead: one pid's unattributed fit-loop time dwarfs its peers'
      (host-side slowness: the planted `delay` fault, GC, slow I/O) —
      fire naming the MAXIMUM-overhead pid.

    Both need >= 2 per-process reports; a single-process run can still
    exercise them through synthesized fake-host reports (the tier-1
    path on jax versions whose 2-proc worker modes skip)."""
    th = {**DEFAULTS, **(thresholds or {})}
    factor = float(th["straggler_factor"])
    floor = float(th["straggler_floor_s"])
    per = [
        (r, sync_seconds(r), loop_overhead_seconds(r)) for r in reports
    ]
    per = [(r, s, o) for r, s, o in per if s > 0.0 or o > 0.0]
    if len(per) < 2:
        return []
    out: List[Dict[str, Any]] = []
    sync = {(_pid_of(r)): s for r, s, _ in per}
    # --- waiters rule ---
    syncs = sorted(per, key=lambda t: t[1])
    lo_r, lo_s, _ = syncs[0]
    hi_r, hi_s, _ = syncs[-1]
    if (
        lo_s > 0.0
        and hi_s - lo_s > floor
        and hi_s > factor * max(lo_s, 1e-9)
    ):
        out.append({
            "check": "straggler",
            "rule": "waiters",
            "pid": int(lo_r.get("pid", 0)),
            "host": _host_of(lo_r),
            "sync_s": round(lo_s, 4),
            "peers_sync_s": round(hi_s, 4),
            "skew": round(hi_s / max(lo_s, 1e-9), 2),
            "sync_by_pid": {k: round(v, 4) for k, v in sync.items()},
        })
        return out
    # --- overhead rule ---
    ovh = sorted(per, key=lambda t: t[2])
    top_r, _, top_o = ovh[-1]
    second_o = ovh[-2][2]
    if top_o > floor and top_o > factor * max(second_o, floor):
        out.append({
            "check": "straggler",
            "rule": "overhead",
            "pid": int(top_r.get("pid", 0)),
            "host": _host_of(top_r),
            "overhead_s": round(top_o, 4),
            "peers_overhead_s": round(second_o, 4),
            "overhead_by_pid": {
                _pid_of(r): round(o, 4) for r, _, o in per
            },
        })
    return out
