"""Typed configuration for the whole framework.

The reference's "config system" is hard-coded ``var``s at the top of each of
its three spark-shell scripts (SURVEY.md C19; reference codes/Bigclamv2.scala:22-31,
codes/bigclam4-7.scala:14-43 -- paths, K, numCore, hyper-parameters). Here it
is a single dataclass covering dataset, model, optimizer, K-selection, mesh,
precision, and checkpointing knobs.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class BigClamConfig:
    """Hyper-parameters of BigCLAM gradient ascent.

    Defaults replicate the reference's magic-constant block exactly
    (SURVEY.md §2.2; reference codes/Bigclamv2.scala:27-31,104-106,214).
    """

    # --- model size ---
    num_communities: int = 100          # K (Bigclamv2.scala:22)

    # --- probability / F clipping (Bigclamv2.scala:28-31) ---
    min_p: float = 1e-4                 # MIN_P_: lower clip of exp(-Fu.Fv)
    max_p: float = 0.9999               # MAX_P_: upper clip of exp(-Fu.Fv)
    min_f: float = 0.0                  # MIN_F_: box lower bound on F entries
    max_f: float = 1000.0               # MAX_F_: box upper bound on F entries

    # --- Armijo backtracking line search (Bigclamv2.scala:104-114) ---
    alpha: float = 0.05                 # Armijo slope factor
    beta: float = 0.1                   # geometric step shrink factor
    max_backtracks: int = 15            # -> 16 candidate steps {1, beta, ..., beta^15}

    # --- outer loop (Bigclamv2.scala:214) ---
    conv_tol: float = 1e-4              # stop when |1 - LLH_new/LLH_old| < conv_tol
    max_iters: int = 1000               # safety cap (reference loops unboundedly)

    # --- K-sweep model selection (bigclam4-7.scala:14-20,116-133,259) ---
    min_com: int = 1000
    max_com: int = 9000
    div_com: int = 100
    ksweep_tol: float = 1e-3            # stop when (1 - LLH_Knew/LLH_Kold) < ksweep_tol

    # --- seeding (conductance locally-minimal, Bigclamv2.scala:42-59) ---
    seed_include_self: bool = True      # v2 ego-net indicator (adj row + self=1.0,
                                        # Bigclamv2.scala:70); False = v3 neighbor-only
                                        # indicator (bigclamv3-7.scala:64-65)
    isolated_phi_sentinel: float = 10.0  # conductance for neighbor-less nodes (v3:51)
    seeding_degree_cap: Optional[int] = None  # sample at most this many
                                        # neighbors per node in the conductance
                                        # scorer (the exact pass is edge-
                                        # quadratic on hubs); None = exact.
                                        # Exact anyway when cap >= max degree.

    # --- quality mode (models/quality.py; NOT reference behavior) ---
    quality_mode: bool = False          # default off = exact reference parity.
                                        # On: noise-floor init + restart
                                        # annealing (fit_quality) — unfreezes
                                        # the all-zero F rows that the
                                        # reference's clamp-at-0 dynamics can
                                        # never move (see PARITY.md)
    init_noise: Optional[float] = None  # U(0, eps) added to F0 and to each
                                        # restart kick. None = auto:
                                        # min(0.02, init_noise_mass *
                                        # (avg_degree + 1) / N). Invariant:
                                        # the kick's contribution to each
                                        # column's sumF (~eps*N/2) must stay
                                        # comparable to a seeded ego-net
                                        # column's mass (~avg_degree + 1) —
                                        # NOT scale with N. Measured best
                                        # eps: 0.01 at N=6K deg 28, 0.002
                                        # at N=60K deg 28 (both = 4(d+1)/N);
                                        # a flat 120/N rule matched only
                                        # because those graphs shared
                                        # deg ~ 28 and failed at low-degree
                                        # small-block regimes
    init_noise_mass: float = 4.0        # kick column mass in units of the
                                        # seeded ego-net column mass
    restart_cycles: int = 40            # max annealing cycles (cycles are
                                        # short — ~5-10 iterations once
                                        # annealing sets in; restart_tol is
                                        # the real stop rule)
    restart_tol: float = 1e-4           # a cycle "gains" when the kept LLH's
                                        # relative improvement >= tol
    restart_patience: int = 3           # stop after this many consecutive
                                        # gainless cycles (a single bad kick
                                        # must not end the annealing)
    seed_exclusion: Optional[bool] = None  # coverage-aware seed selection
                                        # (ops.seeding.select_seeds_covering):
                                        # greedily skip candidates already
                                        # covered by a chosen seed's ego-net,
                                        # so K seeds spread over ~K distinct
                                        # regions instead of piling into the
                                        # lowest-phi one. None = auto (on iff
                                        # quality_mode); False = reference
                                        # ranking (Bigclamv2.scala:56 takes
                                        # the top-K nominees as-is)
    quality_max_p: Optional[float] = None  # quality-mode MAX_P_ override.
                                        # The clip bounds the gradient's
                                        # 1/(1-p) neighbor amplification at
                                        # 1/(1-max_p); a noise-level column
                                        # entry at node u only grows when
                                        # deg(u) * amp > N (its neighbor term
                                        # must beat -sumF), so the parity
                                        # 0.9999 (amp 1e4) freezes annealing
                                        # outright once N > 1e4 * avg_deg —
                                        # measured: max_p=0.99 collapses the
                                        # N=2400 probe to faithful-F1 while
                                        # 0.9999 recovers it. None = auto:
                                        # 1 - 1/(16 N / avg_deg) clamped to
                                        # [max_p, 1 - 1e-15]. The ceiling
                                        # is the f64 representability of
                                        # max_p itself (eps(1.0)/2 ~ 1e-16)
                                        # — NOT an f32 kernel limit: the
                                        # kernels form 1-p as -expm1(-x)
                                        # (ops.objective.edge_terms), exact
                                        # to f32 RELATIVE eps at any
                                        # amplification, so amp scales to
                                        # 1e15 — past Friendster-class
                                        # N = 1e6 * avg_deg (BASELINE 5)
    quality_conv_tol: float = 1e-6      # within-cycle convergence tolerance:
                                        # |LLH| grows with N*K, so the
                                        # reference's relative 1e-4 stops
                                        # large fits after a handful of
                                        # iterations — far from converged
    quality_repair: bool = True         # discrete merge+split repair after
                                        # the annealing loop (models.quality
                                        # .repair_communities): gradient
                                        # dynamics cannot swap whole
                                        # columns, so a column merged over
                                        # two disconnected regions and a
                                        # pair of columns fragmenting one
                                        # region are stable defects; the
                                        # repair frees fragment columns by
                                        # merging dense pairs and re-seeds
                                        # them on the extra components of
                                        # fat columns, then re-anneals and
                                        # keeps the result only if LLH
                                        # improves (measured: F1
                                        # 0.894 -> 0.914, LLH -32037 ->
                                        # -31692 on the N=2400 probe)
    repair_rounds: int = 3              # max discrete rounds (each round =
                                        # one atomize attempt + one
                                        # merge/split attempt; the loop
                                        # stops early once neither accepts)
    quality_reassign: bool = True       # atomize re-tiling inside the
                                        # discrete stage (models.quality
                                        # .atomize_reassign): shatter
                                        # thresholded columns into graph
                                        # components, re-seed K columns on
                                        # the largest deduped atoms, refit,
                                        # keep on LLH gain. Reaches the
                                        # likelihood-optimum band annealing
                                        # cannot (measured at N=12K K=500
                                        # p_in=0.3: LLH -173.8K -> -156.3K,
                                        # the band the round-5 planted
                                        # anchor proved 7-10% above the
                                        # plateau); at sub-identifiability
                                        # p_in the F1 of the re-tiling is
                                        # degenerate and may move either
                                        # way (PARITY.md)

    # --- resilience (bigclam_tpu/resilience; DESIGN.md "Failure model &
    # recovery") ---
    rollback_budget: int = 3            # non-finite-LLH rollbacks allowed per
                                        # fit before escalating to the abort/
                                        # diagnostic path (_abort_nonfinite).
                                        # 0 = abort-only (pre-round-9
                                        # behavior, and no snapshot copies)
    rollback_shrink: float = 0.1        # step_scale multiplier applied at
                                        # each rollback: the Armijo candidate
                                        # ladder is cut so the replay takes
                                        # smaller steps past the blow-up
    rollback_snapshot_every: int = 8    # iterations between in-HBM snapshots
                                        # of the last VERIFIED-finite state
                                        # (ping-pong copy; one extra F-sized
                                        # buffer resident, one device copy
                                        # per interval). A rollback replays
                                        # at most this many iterations
    step_scale: float = 1.0             # global scale on the Armijo candidate
                                        # ladder (step_candidates). Baked
                                        # into the compiled step; the
                                        # rollback path drives it via
                                        # rebuild_step — not a user knob

    # --- sparse membership representation (ops/sparse_members.py;
    # DESIGN.md "Sparse membership representation") ---
    representation: str = "dense"       # "dense" = (N, K) F everywhere (the
                                        # reference semantics, default until
                                        # the TPU artifact lands); "sparse" =
                                        # per-node top-M member lists
                                        # (member ids + weights) — HBM and
                                        # bytes/edge scale with M, not K,
                                        # turning K into a capacity knob.
                                        # Step-baked: two runs differing here
                                        # can never share a compiled step or
                                        # a perf-ledger baseline
    sparse_m: int = 64                  # M: member slots per node (clamped
                                        # to K; M >= K reproduces the dense
                                        # trajectory, M < K is the capacity-
                                        # bounded approximation the LLH-band
                                        # gates cover)
    support_every: int = 1              # iterations between support updates
                                        # (admit candidate communities from
                                        # neighbor member lists, keep top-M
                                        # by weight). 1 = admit every step —
                                        # required for dense parity; larger
                                        # values amortize the admission
                                        # scatter on huge graphs
    sparse_score_block: int = 1 << 22   # support-update scratch budget in
                                        # ELEMENTS: the sort-based
                                        # admission pass works on the
                                        # candidate entries of one node
                                        # block (~block_b*(1+deg)*M of
                                        # them) — block size is picked to
                                        # keep that near this budget. No
                                        # K-sized axis anywhere: the
                                        # support pass stays flat in K
    sparse_comm_cap: int = 0            # sparse-allreduce buffer capacity
                                        # (touched community ids exchanged
                                        # per shard). 0 = auto: sized from
                                        # the initial state's per-shard
                                        # touched counts x
                                        # sparse_cap_slack at init_state
    sparse_cap_slack: float = 2.0       # auto-cap headroom over the initial
                                        # per-shard touched-id count (the
                                        # support only grows by neighbor
                                        # admission, so 2x covers the
                                        # planted/power-law workloads;
                                        # runtime overflow falls back to a
                                        # dense psum for that step)
    sparse_dense_fallback: float = 0.5  # density threshold: when the
                                        # exchange cap exceeds this fraction
                                        # of K, the sparse allreduce would
                                        # move more bytes than the dense
                                        # psum — the trainer statically
                                        # keeps psum(sumF) and records why

    # --- numerics ---
    dtype: str = "float32"              # F / gradient dtype on device
    accum_dtype: Optional[str] = None   # LLH accumulation dtype; None = dtype
    seed: int = 0                       # PRNG seed for Bernoulli(0.5) F-row padding

    # --- execution shape ---
    ring_overlap: bool = True           # double-buffered ring rotations
                                        # (parallel.ring.rotate_scan): the
                                        # ppermute moving phase r+1's F shard
                                        # is issued CONCURRENTLY with phase
                                        # r's edge sweep, so the inter-chip
                                        # hop hides behind compute whenever
                                        # the sweep outlasts the shard
                                        # transfer. False = strictly
                                        # serialized sweep -> hop schedule
                                        # (an optimization_barrier pins the
                                        # order) — the A/B fallback for
                                        # hosts/interconnects where the
                                        # in-flight buffer's extra HBM or
                                        # the async collective hurts
    donate_state: bool = True           # fit loops donate the dropped
                                        # previous TrainState's buffers back
                                        # to the next step (ping-pong
                                        # scratch, models.bigclam
                                        # .run_fit_loop): XLA reuses the old
                                        # F storage for the new F instead of
                                        # holding both plus the in-flight
                                        # copy. Host-only flag — the
                                        # donating entry is compiled lazily
                                        # and only when used
    edge_chunk: int = 1 << 20           # directed edges per on-device chunk,
                                        # further capped by gather bytes (see
                                        # models.bigclam.edge_chunk_bound).
                                        # Fewer chunks = fewer scan steps
                                        # re-reading the (N, K) carry
                                        # accumulators (measurably cheaper)
    mesh_shape: Tuple[int, int] = (1, 1)  # (node-shards, k-shards) = (DP, TP-analog)
    partition: str = "1d"               # node-axis partition of the dense
                                        # sharded families (ISSUE 16):
                                        # "1d" = every chip gathers full F
                                        # (all_gather over "nodes"); "2d" =
                                        # (rows x cols) edge-block layout
                                        # where each chip exchanges only its
                                        # baked closure rows
                                        # (parallel.twod). STEP-BAKED and a
                                        # perf-ledger match-key field: 1d
                                        # and 2d runs never share a compiled
                                        # step or a baseline
    replica_cols: int = 1               # C in the (R x C) 2d mesh; the
                                        # node-shard count dp must divide by
                                        # it (R = dp // C). 1 keeps the 1D
                                        # edge layout with the closure
                                        # exchange replacing all_gather(F).
                                        # Ignored under partition="1d"
    grad_exchange: str = "closure"      # 2D backward-path reduction over the
                                        # cols axis (ISSUE 17): "closure" =
                                        # touched-rows-only gather/all_to_all/
                                        # scatter-add over the baked closure
                                        # unions (psum only the capped union;
                                        # runtime overflow falls back to a
                                        # dense psum for that step, counted);
                                        # "dense" = the PR 16 partial-group
                                        # psum over the full row band (the
                                        # A/B + baseline path). STEP-BAKED
                                        # and a perf-ledger match-key field:
                                        # the two exchanges never share a
                                        # compiled step or a baseline. No-op
                                        # at replica_cols=1 (no cols
                                        # reduction exists)
    closure_grad_cap: int = 0           # closure grad-exchange buffer
                                        # capacity (rows sent per cols peer
                                        # pair). 0 = auto: the largest baked
                                        # pair union x sparse_cap_slack,
                                        # clamped to the row-band size.
                                        # Runtime overflow -> dense-psum
                                        # fallback for that step (mirrors
                                        # sparse_comm_cap's counters)
    use_pallas: Optional[bool] = None   # fused VMEM candidate kernel; None =
                                        # auto (on for TPU backends when tile
                                        # constraints are met)
    use_pallas_csr: Optional[bool] = None  # blocked-CSR MXU kernels
                                        # (ops.pallas_csr) replacing the whole
                                        # edge sweep; None = auto (on for TPU
                                        # when tiling constraints + the fd
                                        # gather memory budget hold). When on,
                                        # it supersedes use_pallas.
    csr_block_b: int = 256              # node rows per F block in VMEM
                                        # (256/512 tuned fastest on v5e:
                                        # one-hot matmul cost scales with B)
    csr_tile_t: int = 512               # edges per kernel tile
    csr_store_pad_tiles: int = 0        # store-native tile builds (ISSUE 9):
                                        # uniform per-shard tile-count pad
                                        # the hosts agree on. 0 = auto (a
                                        # tiny cross-host max exchange of
                                        # the local tile counts — one int);
                                        # explicit values let pod jobs skip
                                        # the exchange and keep compiled
                                        # shapes deterministic across
                                        # restarts. Must be >= every
                                        # host's true tile count (loudly
                                        # checked). Host-only: tile arrays
                                        # ride as jit arguments, so shape
                                        # changes retrace without a step-
                                        # key change
    csr_k_block: int = 0                # K columns per kernel invocation on
                                        # the single-chip K-blocked path
                                        # (train_pass_csr_grouped_kblocked).
                                        # 0 = auto: whole K when it fits
                                        # VMEM, else the largest 128-multiple
                                        # divisor of k_pad that does — the
                                        # single-chip large-K mode (K ≳ 2500
                                        # otherwise falls back to XLA)
    csr_fused: Optional[bool] = None    # fused edge superstep (ISSUE 13,
                                        # ops.pallas_fused): dst rows DMA'd
                                        # per-tile into VMEM inside the
                                        # kernel (double-buffered against
                                        # compute — no HBM-resident fd
                                        # gather), grad kept VMEM-resident
                                        # per block, Armijo ladder + select
                                        # + non-negative projection fused
                                        # into the same kernel pass. None =
                                        # auto: ON whenever the blocked-CSR
                                        # kernels engage; False = the
                                        # pre-r17 split-kernel schedule
                                        # (the A/B + baseline path).
                                        # Step-baked: fused and split runs
                                        # never share a compiled step or a
                                        # perf-ledger baseline
    sparse_pallas_merge: Optional[bool] = None  # sparse member-list merge
                                        # as a Pallas compare-block kernel
                                        # (ops.sparse_members
                                        # .member_lookup_pallas) instead of
                                        # the gather-bound XLA searchsorted
                                        # merge. None = auto (on for TPU
                                        # backends, or under
                                        # pallas_interpret); step-baked
    pallas_interpret: bool = False      # run Pallas kernels in interpret mode
                                        # (CPU testing of the kernel paths)

    # --- model-health diagnostics (ops/diagnostics.py + obs/health.py;
    # DESIGN.md "Model-health diagnostics") ---
    health_every: int = 0               # iterations between device-fused
                                        # health packs (grad/update norms,
                                        # effective Armijo step, community
                                        # mass stats, sparse support churn /
                                        # cap occupancy) computed INSIDE the
                                        # jitted step and emitted as `health`
                                        # telemetry events. 0 = off: steps
                                        # return health=None and the
                                        # trajectory is bit-identical to the
                                        # pre-health trainers. STEP-BAKED
                                        # (not in _HOST_ONLY_FIELDS): two
                                        # cadences never share a compiled
                                        # step. The CLI defaults this to 10
                                        # (--health-every; anomaly detection
                                        # needs a telemetry dir to land in)

    # --- checkpointing / logging ---
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 0           # iterations between checkpoints; 0 = off
    metrics_path: Optional[str] = None  # JSONL per-step records; None = stdout only

    @property
    def step_candidates(self) -> Tuple[float, ...]:
        """The candidate step sizes {1, beta, beta^2, ..., beta^max_backtracks}.

        Same set as the reference's listSearch (Bigclamv2.scala:108-113, which
        prepends and so ends up smallest-first). Order here is descending, and
        consumers must not rely on it: the chosen step is the max accepted
        (Bigclamv2.scala:145), which is order-independent.
        """
        steps = [1.0]
        s = 1.0
        for _ in range(self.max_backtracks):
            s *= self.beta
            steps.append(s)
        if self.step_scale != 1.0:
            # non-finite rollback's step cut (resilience): the whole ladder
            # shrinks, the candidate COUNT (and accept_hist shape) does not
            steps = [self.step_scale * v for v in steps]
        return tuple(steps)

    def replace(self, **kw) -> "BigClamConfig":
        return dataclasses.replace(self, **kw)
