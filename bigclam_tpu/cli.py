"""Command-line interface: the reference's "config mechanism" was editing
hard-coded vars in three spark-shell scripts (SURVEY.md §5/C19); here one CLI
covers fitting, K-sweeps and ground-truth evaluation.

    python -m bigclam_tpu.cli ingest --graph data.txt --cache-dir data.cache
    python -m bigclam_tpu.cli fit   --graph data.txt --k 100 --out cmty.txt
    python -m bigclam_tpu.cli fit   --graph data.cache --k 100 --out cmty.txt
    python -m bigclam_tpu.cli sweep --graph data.txt --min-com 50 --max-com 200
    python -m bigclam_tpu.cli eval  --pred cmty.txt --truth truth.cmty
    python -m bigclam_tpu.cli profile --graph data.txt --k 100 --steps 20
    python -m bigclam_tpu.cli perf diff --ledger perf/ledger.jsonl

`fit`/`sweep` accept either a SNAP text path or a graph-cache directory
compiled by `ingest` (binary shards, mmap fast reload); passing a text path
plus --cache-dir compiles the cache on first use and reloads from it after.
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--graph", required=True,
        help="SNAP edge-list path, or a graph-cache dir from `ingest`",
    )
    p.add_argument(
        "--cache-dir", default=None,
        help="graph-cache directory: compile the text --graph into it on "
             "first use (see `ingest`), then reload from the binary shards",
    )
    p.add_argument("--dtype", default="float32", choices=["float32", "float64"])
    p.add_argument("--max-iters", type=int, default=1000)
    p.add_argument("--conv-tol", type=float, default=1e-4)
    p.add_argument("--alpha", type=float, default=0.05)
    p.add_argument("--beta", type=float, default=0.1)
    p.add_argument("--max-backtracks", type=int, default=15)
    p.add_argument(
        "--edge-chunk", type=int, default=None,
        help="directed edges per device chunk (default: config default)",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--init", default="conductance",
        choices=["conductance", "random", "rowkeyed"],
        help="F initialization (conductance seeding is the reference "
             "default). rowkeyed: the {0,1} row-keyed counter init "
             "(models.bigclam.rowkeyed_init_rows) — on --store-native "
             "runs each host seeds ONLY its own row range, no host ever "
             "materializes the O(N*K) F0 array (ROADMAP 1a)",
    )
    p.add_argument(
        "--mesh", default=None,
        help="'DP,TP' device mesh, e.g. 4,2 (default: single device)",
    )
    p.add_argument(
        "--distributed", action="store_true",
        help="multi-host run: join the jax.distributed process group "
             "(coordinator from JAX_COORDINATOR_ADDRESS) and build the mesh "
             "over every process's devices, DCN-aware (slice-major nodes "
             "axis). Run the same command on every host.",
    )
    p.add_argument(
        "--balance", action="store_true",
        help="degree-balanced node relabeling before sharding (evens "
             "per-shard edge counts on power-law graphs; results are mapped "
             "back to original ids)",
    )
    p.add_argument(
        "--schedule", default="allgather", choices=["allgather", "ring"],
        help="F-row exchange schedule for --mesh runs: allgather materializes"
             " a full F per device (fastest at small N); ring rotates shards"
             " around the ICI ring (O(N/dp) peak memory, pod-scale)",
    )
    p.add_argument(
        "--partition", default="1d", choices=["1d", "2d"],
        help="node-axis partition for --mesh runs: 1d (default) shards "
             "nodes dp ways and all-gathers F; 2d tiles the edge set "
             "over a (rows, cols) grid — each chip gathers only its row "
             "group's src blocks plus the capped closure rows its edges "
             "touch (communication-avoiding at large K; see DESIGN.md)",
    )
    p.add_argument(
        "--replica-cols", type=int, default=1,
        help="columns of the --partition 2d grid (rows = p / cols; must "
             "divide the chip count; 1 reproduces the 1d trajectory "
             "bit-for-bit on the 2d schedule)",
    )
    p.add_argument(
        "--csr-kernels", default="auto", choices=["auto", "on", "off"],
        help="blocked-CSR Pallas kernel path (auto: on for TPU backends "
             "when the layout fits; on: require, error if unsupported)",
    )
    p.add_argument(
        "--csr-fused", default="auto", choices=["auto", "off"],
        help="fused edge superstep (ops/pallas_fused: in-kernel "
             "double-buffered dst-row DMA, no HBM fd gather, Armijo "
             "select + projection in-kernel). auto (default): engaged "
             "wherever the CSR kernels engage; off: the pre-r17 split "
             "kernel suite — the A/B + perf-baseline path (fused and "
             "split runs never share a ledger baseline)",
    )
    p.add_argument(
        "--representation", default="dense", choices=["dense", "sparse"],
        help="affiliation-state representation: dense (N, K) F (the "
             "reference semantics, default) or sparse per-node top-M "
             "member lists (ops/sparse_members.py) — HBM and bytes/edge "
             "scale with --sparse-m instead of K, turning K into a "
             "capacity knob. Dense stays the default until the TPU "
             "artifact lands",
    )
    p.add_argument(
        "--sparse-m", type=int, default=64,
        help="member slots per node on --representation sparse (M; "
             "clamped to K — M >= K reproduces the dense trajectory)",
    )
    p.add_argument(
        "--support-every", type=int, default=1,
        help="iterations between sparse support updates (candidate-"
             "community admission from neighbor lists; 1 = every step, "
             "required for dense parity)",
    )
    p.add_argument(
        "--seeding-degree-cap", type=int, default=None,
        help="sample at most this many neighbors per node in conductance "
             "seeding (exact pass is edge-quadratic on hubs; exact when "
             "cap >= max degree)",
    )
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every", type=int, default=0)
    p.add_argument("--metrics", default=None, help="JSONL metrics path")
    p.add_argument("--profile-dir", default=None, help="jax.profiler trace dir")
    p.add_argument(
        "--telemetry-dir", default=None,
        help="run-telemetry directory (bigclam_tpu.obs): events.jsonl + "
             "run_report.json — step metrics, stage transitions, device-"
             "memory watermarks, compile counters, stall heartbeat; render "
             "with `cli report <dir>`",
    )
    p.add_argument(
        "--heartbeat-s", type=float, default=300.0,
        help="stall-heartbeat deadline with --telemetry-dir: emit a stall "
             "event when no step/stage completes within this many seconds "
             "(0 disables; --quiet silences the stderr echo, never the "
             "JSONL)",
    )
    p.add_argument(
        "--health-every", type=int, default=10,
        help="iterations between device-fused model-health samples (grad/"
             "update norms, effective Armijo step, dead communities, "
             "membership churn; sparse runs add support churn + comm-cap "
             "occupancy), emitted as `health` events with anomaly "
             "detection (divergence/plateau/oscillation/dead-communities/"
             "cap-pressure) when --telemetry-dir is active; render live "
             "with `cli watch <dir>`. 0 disables — the step then computes "
             "nothing and the trajectory is bit-identical either way",
    )
    p.add_argument(
        "--perf-ledger", default=None,
        help="append this run's perf record (step-time percentiles, eps, "
             "compile count, per-span totals, config/host digest) to a "
             "perf-ledger JSONL at finalize; compare runs with `cli perf "
             "diff`. Equivalent to setting BIGCLAM_PERF_LEDGER. Requires "
             "--telemetry-dir",
    )
    p.add_argument(
        "--quiet", action="store_true",
        help="silence per-step echo, engagement lines, and the heartbeat's "
             "stderr warnings (telemetry JSONL stays complete)",
    )
    p.add_argument(
        "--resume", default="auto", choices=["auto", "never"],
        help="auto (default): restore the newest VALID checkpoint from "
             "--checkpoint-dir (falling back past truncated/corrupt newer "
             "ones), record the attempt in the telemetry resume lineage, "
             "and continue the bit-identical trajectory; never: cold-start "
             "(existing checkpoints are kept but ignored; new ones still "
             "save)",
    )
    p.add_argument(
        "--retry-budget", type=int, default=2,
        help="transient-failure retries for the whole fit attempt "
             "(resilience supervisor: each retry RESUMES from the newest "
             "checkpoint; fatal errors never retry; 0 disables)",
    )
    p.add_argument(
        "--no-self-heal", action="store_true",
        help="disable shard quarantine + re-ingest: a crc-failed cache "
             "shard rejects the run (default: quarantine the blob, rebuild "
             "it from the source edge list, continue)",
    )
    p.add_argument(
        "--heartbeat-escalate", type=int, default=3,
        help="consecutive stall-heartbeat deadlines before a "
             "stall_escalated event fires (0 disables escalation; the "
             "watchdog then only keeps emitting stall events)",
    )
    p.add_argument(
        "--platform", default=None, choices=["cpu", "tpu"],
        help="force a JAX platform (the env may pin one; this overrides it)",
    )
    p.add_argument(
        "--seed-backend", default="auto",
        choices=["auto", "baked", "numpy", "dense", "sampled",
                 "sampled_device"],
        help="conductance scorer backend (ops.seeding.conductance): "
             "sampled_device runs the degree-capped estimator on the "
             "accelerator — the C5 path past the 16,384-node dense bound "
             "(scripts/device_seeding_bench.py measures the backends on "
             "your hardware). On a graph-cache --graph, auto reads the "
             "INGEST-BAKED scores when present (no re-streaming); baked "
             "requires them (error with a re-ingest hint otherwise)",
    )
    p.add_argument(
        "--store-native", action="store_true",
        help="with --mesh/--distributed and a graph-cache --graph: feed "
             "the trainer per-host from its own shard files "
             "(StoreSharded/StoreRing — edge blocks, CSR tiles, and ring "
             "buckets built from HostShard local rows; no host holds the "
             "global edge set on the training path). Balance comes from "
             "the cache (`ingest --balance`), trajectories are bit-"
             "identical to the in-memory trainers",
    )


def _make_supervisor(args, cfg, tel):
    """The entry-point retry supervisor: --retry-budget extra attempts for
    transient-classified failures (each re-entering the fit WITH its
    checkpoints, so retry = resume), stall-escalation hook attached to the
    telemetry heartbeat when one is running."""
    from bigclam_tpu.resilience import RetryPolicy, Supervisor

    sup = Supervisor(
        RetryPolicy(
            transient_attempts=max(getattr(args, "retry_budget", 2), 0) + 1,
            seed=cfg.seed,
        )
    )
    if tel is not None:
        sup.attach(tel)
    return sup


def _open_telemetry(args, entry: str):
    """Create + install the run telemetry when --telemetry-dir was given
    (None otherwise). device telemetry is off for jax-free entries
    (ingest); --distributed defers the single-writer gate until the
    process group is joined (initialize_distributed commits it)."""
    tdir = getattr(args, "telemetry_dir", None)
    ledger = getattr(args, "perf_ledger", None)
    if ledger and not tdir:
        print(
            "warning: --perf-ledger has no effect without "
            "--telemetry-dir (no run telemetry, no perf record)",
            file=sys.stderr,
        )
    if not tdir:
        return None
    from bigclam_tpu.obs import RunTelemetry, install

    return install(
        RunTelemetry(
            tdir,
            entry=entry,
            heartbeat_s=getattr(args, "heartbeat_s", 0.0),
            quiet=getattr(args, "quiet", False),
            # ingest, serve, route, and fleet are jax-free entries
            # (serve only imports jax lazily for fold-in; the router
            # and the supervisor never do): device sampling would
            # initialize a backend they never use
            device_memory=entry not in ("ingest", "serve", "route",
                                        "fleet"),
            auto_gate=not getattr(args, "distributed", False),
            heartbeat_escalate=getattr(args, "heartbeat_escalate", 0),
            # passed THROUGH rather than via os.environ: an env mutation
            # would leak the ledger into later in-process main() calls
            # and child processes (BIGCLAM_PERF_LEDGER stays the opt-in
            # for bench/gate scripts)
            ledger_path=ledger,
        )
    )


def _close_telemetry(tel) -> None:
    if tel is None:
        return
    from bigclam_tpu.obs import uninstall

    tel.finalize()
    uninstall(tel)


def _load_graph(args):
    """Graph for fit/sweep: text+--cache-dir compiles once then reloads;
    everything else (text OR cache dir) goes through the store/parser
    directly. Cache loads self-heal crc-failed shards (quarantine +
    re-ingest) unless --no-self-heal. The opened GraphStore (when the
    graph came from a cache) is stashed on args._store so seeding can
    read ingest-baked seed scores and --store-native can feed the
    trainers per-host."""
    from bigclam_tpu.graph import build_graph
    from bigclam_tpu.graph.store import (
        GraphStore,
        compile_graph_cache,
        is_cache_dir,
    )

    heal = not getattr(args, "no_self_heal", False)
    path = args.graph
    cache = getattr(args, "cache_dir", None)
    args._store = None
    if cache and not is_cache_dir(path):
        if not is_cache_dir(cache):
            print(
                f"note: compiling graph cache {cache} from {path}",
                file=sys.stderr,
            )
            args._store = compile_graph_cache(
                path, cache,
                seed=getattr(args, "seed", 0),
                # forward the fit's cap so the bake runs the estimator the
                # run will trust (ShardSeedScores.matches) — and so a
                # capped run never pays the exact edge-quadratic pass
                seed_cap=getattr(args, "seeding_degree_cap", None),
            )
            return args._store.load_graph()
        args._store = GraphStore.open(cache, self_heal=heal)
        return args._store.load_graph()
    if is_cache_dir(path):
        args._store = GraphStore.open(path, self_heal=heal)
        return args._store.load_graph()
    return build_graph(path, self_heal=heal)


def _build(args, k: int):
    from bigclam_tpu.config import BigClamConfig

    if getattr(args, "quiet", False):
        # one knob: --quiet silences the model-build engagement lines too
        import os

        os.environ["BIGCLAM_QUIET"] = "1"

    cfg = BigClamConfig(
        num_communities=k,
        dtype=args.dtype,
        max_iters=args.max_iters,
        conv_tol=args.conv_tol,
        alpha=args.alpha,
        beta=args.beta,
        max_backtracks=args.max_backtracks,
        edge_chunk=args.edge_chunk or BigClamConfig.edge_chunk,
        seed=args.seed,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
        metrics_path=args.metrics,
        min_com=getattr(args, "min_com", 1000),
        max_com=getattr(args, "max_com", 9000),
        div_com=getattr(args, "div_com", 100),
        ksweep_tol=getattr(args, "ksweep_tol", 1e-3),
        use_pallas_csr={"auto": None, "on": True, "off": False}[
            args.csr_kernels
        ],
        csr_fused={"auto": None, "off": False}[
            getattr(args, "csr_fused", "auto")
        ],
        seeding_degree_cap=args.seeding_degree_cap,
        representation=getattr(args, "representation", "dense"),
        sparse_m=getattr(args, "sparse_m", 64),
        support_every=getattr(args, "support_every", 1),
        health_every=max(getattr(args, "health_every", 0) or 0, 0),
        partition=getattr(args, "partition", "1d"),
        replica_cols=max(getattr(args, "replica_cols", 1) or 1, 1),
    )
    g = _load_graph(args)
    return g, cfg


def _make_model(g, cfg, args):
    if cfg.representation == "sparse" and getattr(args, "quality", False):
        # quality mode's annealing drives dense reset_state(F) cycles —
        # not refactored onto slot arrays yet
        raise SystemExit(
            "error: --quality is not supported with --representation "
            "sparse yet (the annealing schedule is dense-state-resident)"
        )
    if cfg.representation == "sparse" and cfg.use_pallas_csr:
        # "on" means require — the sparse trainers only have the XLA
        # member-list merge (MXU kernel is an open ROADMAP item), so
        # honoring the contract means refusing, not silently falling back
        raise SystemExit(
            "error: --csr-kernels on is not supported with "
            "--representation sparse (the CSR tile kernels are a dense-F "
            "layout; the sparse path has its own Pallas member-merge "
            "kernel, auto-engaged on TPU — use --csr-kernels auto)"
        )
    store_native = getattr(args, "store_native", False)
    if store_native and not (args.mesh or args.distributed):
        raise SystemExit(
            "error: --store-native needs a sharded run (--mesh or "
            "--distributed) — the store trainers load one shard slice "
            "per host"
        )
    if store_native and cfg.representation == "sparse":
        raise SystemExit(
            "error: --store-native is not supported with "
            "--representation sparse yet (the sparse trainers build "
            "member-list state from the in-memory graph)"
        )
    if cfg.partition == "2d":
        # the 2D closure-gather schedule (ISSUE 16): its own trainer
        # family on a (rows, cols, k) mesh — refuse the combinations it
        # does not speak up front, with the knob that does
        if not args.mesh:
            raise SystemExit(
                "error: --partition 2d needs --mesh p,1 (the 2D edge-"
                "block layout is a sharded schedule)"
            )
        if args.distributed:
            raise SystemExit(
                "error: --partition 2d is single-controller for now "
                "(the multi-host 2D mesh rides the ROADMAP item 1 pod "
                "drill)"
            )
        if cfg.representation == "sparse":
            raise SystemExit(
                "error: --partition 2d runs the dense-F closure-gather "
                "schedule, and the sparse top-M member exchange shards "
                "members over the 1d node axis — the two layouts have "
                "no common F placement to train on. Alternatives: keep "
                "--representation sparse on the 1d mesh (its capped "
                "member exchange already avoids the dense all-gather), "
                "or go dense to take the 2d closure schedule "
                "(`cli preflight --partition 2d` prices both)"
            )
        if args.schedule == "ring":
            raise SystemExit(
                "error: --partition 2d is its own closure-gather "
                "schedule — each chip gathers only the closure rows its "
                "edge block touches, so there is no resident F ring to "
                "rotate (ring shards dst-F around the 1d node axis). "
                "Alternatives: drop --schedule ring (2d replaces what "
                "ring saves), or keep --schedule ring on the 1d mesh"
            )
        import jax

        from bigclam_tpu.parallel import (
            StoreTwoDShardedBigClamModel,
            TwoDShardedBigClamModel,
            make_mesh_2d,
            twod_mesh_shape,
        )

        dp, tp = (int(x) for x in args.mesh.split(","))
        if tp != 1:
            raise SystemExit(
                "error: --partition 2d needs --mesh p,1 — the k axis "
                "rides the 2D mesh unsharded (use --replica-cols to "
                "shape the (rows, cols) grid)"
            )
        rows, cols = twod_mesh_shape(cfg, dp)
        mesh = make_mesh_2d((rows, cols), jax.devices()[:dp])
        if store_native:
            store = getattr(args, "_store", None)
            if store is None:
                raise SystemExit(
                    "error: --store-native needs --graph (or "
                    "--cache-dir) to be a compiled graph cache (run "
                    "`cli ingest` first)"
                )
            return StoreTwoDShardedBigClamModel(store, cfg, mesh)
        return TwoDShardedBigClamModel(g, cfg, mesh, balance=args.balance)
    if args.mesh or args.distributed:
        import jax

        from bigclam_tpu.parallel import (
            RingBigClamModel,
            ShardedBigClamModel,
            SparseShardedBigClamModel,
            StoreRingBigClamModel,
            StoreShardedBigClamModel,
            make_mesh,
            make_multihost_mesh,
        )

        if args.distributed:
            from bigclam_tpu.parallel import initialize_distributed

            if not initialize_distributed() and jax.process_count() == 1:
                print(
                    "warning: --distributed but no coordinator found "
                    "(set JAX_COORDINATOR_ADDRESS + JAX_NUM_PROCESSES + "
                    "JAX_PROCESS_ID on every host); continuing "
                    "single-process over local devices only",
                    file=sys.stderr,
                )
            shape = None
            if args.mesh:
                shape = tuple(int(x) for x in args.mesh.split(","))
            mesh = make_multihost_mesh(shape)
        else:
            dp, tp = (int(x) for x in args.mesh.split(","))
            mesh = make_mesh((dp, tp), jax.devices()[: dp * tp])
        if cfg.representation == "sparse":
            if args.schedule == "ring":
                raise SystemExit(
                    "error: --schedule ring is not supported with "
                    "--representation sparse yet (the sparse exchange is "
                    "an M-column all_gather + sparse allreduce)"
                )
            return SparseShardedBigClamModel(
                g, cfg, mesh, balance=args.balance
            )
        if store_native:
            store = getattr(args, "_store", None)
            if store is None:
                raise SystemExit(
                    "error: --store-native needs --graph (or --cache-dir) "
                    "to be a compiled graph cache (run `cli ingest` first)"
                )
            if args.balance:
                raise SystemExit(
                    "error: --store-native takes balance from the cache; "
                    "re-ingest with `cli ingest --balance` instead of "
                    "--balance"
                )
            cls = (
                StoreRingBigClamModel
                if args.schedule == "ring"
                else StoreShardedBigClamModel
            )
            return cls(store, cfg, mesh)
        cls = RingBigClamModel if args.schedule == "ring" else ShardedBigClamModel
        return cls(g, cfg, mesh, balance=args.balance)
    if cfg.representation == "sparse":
        from bigclam_tpu.models import SparseBigClamModel

        return SparseBigClamModel(g, cfg)
    from bigclam_tpu.models import BigClamModel

    return BigClamModel(g, cfg, k_multiple=128 if cfg.dtype == "float32" else 1)


def _mesh_label(mesh) -> str:
    """'dpxtp' identity of a mesh for the perf ledger's match key; a 2D
    (rows, cols, k) mesh labels as 'rowsxcols' — the ledger's partition
    field keeps it from colliding with a 1D 'dpxtp' string."""
    from bigclam_tpu.parallel.mesh import (
        COLS_AXIS, K_AXIS, NODES_AXIS, ROWS_AXIS,
    )

    if ROWS_AXIS in mesh.shape:
        return f"{mesh.shape[ROWS_AXIS]}x{mesh.shape[COLS_AXIS]}"
    return f"{mesh.shape[NODES_AXIS]}x{mesh.shape[K_AXIS]}"


def _init_F(g, cfg, args):
    from bigclam_tpu.ops import seeding

    if args.init == "rowkeyed":
        # host-global materialization of the row-keyed counter init —
        # entries that skip the fit's F0=None fast path (profile)
        # still get the SAME bits as `fit --init rowkeyed`
        from bigclam_tpu.models.bigclam import rowkeyed_init_F

        return rowkeyed_init_F(g, cfg)
    if args.init == "conductance":
        backend = getattr(args, "seed_backend", "auto")
        store = getattr(args, "_store", None)
        quiet = getattr(args, "quiet", False)
        phi = None
        if backend in ("auto", "baked") and store is not None:
            # ingest-baked seed scores: the conductance pass (the dominant
            # seeding cost) already ran at ingest; read it instead of
            # re-streaming the graph (ISSUE 9)
            try:
                scores = store.load_seed_scores()
                if scores.matches(cfg.seeding_degree_cap, cfg.seed):
                    phi = scores.phi
                    if not quiet:
                        print(
                            "[bigclam] seeding: using ingest-baked seed "
                            "scores from the graph cache",
                            file=sys.stderr,
                        )
                else:
                    # the bake's estimator disagrees with this run's
                    # seeding config — silently using it would change the
                    # ranking vs the same fit on the raw text graph
                    msg = (
                        f"baked seed scores (cap={scores.cap}, "
                        f"seed={scores.seed}) do not match this run "
                        f"(--seeding-degree-cap {cfg.seeding_degree_cap}, "
                        f"--seed {cfg.seed}); re-ingest with matching "
                        "--seed-cap/--seed"
                    )
                    if backend == "baked":
                        raise SystemExit(f"error: {msg}")
                    if not quiet:
                        print(
                            f"note: {msg} — falling back to the "
                            "streaming conductance pass",
                            file=sys.stderr,
                        )
            except ValueError as e:
                if backend == "baked":
                    raise SystemExit(f"error: {e}")
                if not quiet:
                    print(
                        f"note: {e}; falling back to the streaming "
                        "conductance pass",
                        file=sys.stderr,
                    )
        elif backend == "baked":
            raise SystemExit(
                "error: --seed-backend baked needs the graph to come "
                "from a compiled cache with baked seed scores (run "
                "`cli ingest` and pass the cache dir as --graph)"
            )
        seeds = seeding.conductance_seeds(
            g, cfg,
            backend="auto" if backend == "baked" else backend,
            phi=phi,
        )
        return seeding.init_F(g, seeds, cfg)
    rng = np.random.default_rng(cfg.seed)
    return rng.integers(
        0, 2, size=(g.num_nodes, cfg.num_communities)
    ).astype(np.float64)


def cmd_fit(args) -> int:
    tel = _open_telemetry(args, "fit")
    try:
        return _cmd_fit(args, tel)
    finally:
        _close_telemetry(tel)


def _cmd_fit(args, tel=None) -> int:
    from bigclam_tpu.ops import extraction
    from bigclam_tpu.utils import CheckpointManager, MetricsLogger
    from bigclam_tpu.utils.profiling import StageProfile, trace

    # stage boundaries forward into the telemetry (event + device-memory
    # watermark + heartbeat beat) when --telemetry-dir is active
    prof = StageProfile()
    with prof.stage("graph_load"):
        g, cfg = _build(args, args.k)
    if getattr(args, "seed_exclusion", None) is not None:
        # orthogonal to --quality: an explicit True engages the covering
        # walk even for parity fits (the auto rule is on-iff-quality)
        cfg = cfg.replace(seed_exclusion=bool(args.seed_exclusion))
    quality_kw = {
        key: val
        for key, val in (
            ("init_noise", args.init_noise),
            ("restart_cycles", args.restart_cycles),
            ("restart_tol", args.restart_tol),
            ("quality_max_p", getattr(args, "quality_max_p", None)),
        )
        if val is not None
    }
    if getattr(args, "quality", False):
        cfg = cfg.replace(quality_mode=True, **quality_kw)
    elif quality_kw or getattr(args, "device_annealing", False):
        noop = sorted(quality_kw) + (
            ["device_annealing"]
            if getattr(args, "device_annealing", False)
            else []
        )
        print(
            f"warning: {noop} have no effect without --quality",
            file=sys.stderr,
        )
    if args.checkpoint_dir and cfg.checkpoint_every <= 0:
        # a checkpoint dir without a cadence would restore but never save
        cfg = cfg.replace(checkpoint_every=50)
        print(
            "note: --checkpoint-dir given without --checkpoint-every; "
            "defaulting to every 50 iterations",
            file=sys.stderr,
        )
    if args.init == "rowkeyed" and cfg.quality_mode:
        raise SystemExit(
            "error: --init rowkeyed is not supported with --quality "
            "(the annealing schedule owns its noise-floor init)"
        )
    if getattr(args, "follow", None):
        # validate the follow preconditions BEFORE the (possibly hours-
        # long) fit: a misconfigured loop must refuse up front, not
        # after the fit it would have discarded
        if getattr(args, "_store", None) is None:
            raise SystemExit(
                "error: --follow needs a compiled graph cache (--graph "
                "<cache-dir> or --cache-dir): deltas re-ingest shard "
                "ranges, not text files"
            )
        if not getattr(args, "publish_dir", None):
            raise SystemExit(
                "error: --follow needs --publish-dir (each refit "
                "publishes a snapshot generation the server swaps to)"
            )
        if getattr(args, "publish_shards", 0) > 1:
            raise SystemExit(
                "error: --follow publishes single archives — it cannot "
                "feed a fleet yet (drop --publish-shards; re-run `cli "
                "fit --publish-shards` per generation instead)"
            )
        if args.mesh or args.distributed or cfg.quality_mode or (
            cfg.representation == "sparse"
        ):
            raise SystemExit(
                "error: --follow supports single-chip dense fits for "
                "now (the sharded/sparse refit loop rides the ROADMAP "
                "item 1 pod drill)"
            )
    with prof.stage("model_build"):
        model = _make_model(g, cfg, args)
    if tel is not None:
        # the process group (if any) was joined inside _make_model: the
        # single-writer gate is decidable now even when
        # initialize_distributed never ran (single-process fallback)
        tel.commit_gate()
    with prof.stage("seeding"):
        # rowkeyed: F0 = None defers to the model's init_state — on the
        # store-backed trainers each host generates only its own row
        # range (ISSUE 15 satellite); no host-global array exists here
        F0 = None if args.init == "rowkeyed" else _init_F(g, cfg, args)
    ckpt = (
        CheckpointManager(args.checkpoint_dir) if args.checkpoint_dir else None
    )
    resume = getattr(args, "resume", "auto") != "never"
    if ckpt is not None and resume:
        # --resume auto is actually resuming: record the attempt in the
        # telemetry resume lineage (resume event + resume_lineage.json)
        # BEFORE the fit, so even a re-crashed attempt leaves its trace.
        # The step recorded is the newest VALID one — what restore() will
        # use — not the newest filename (which may be corrupt).
        valid_step = ckpt.latest_valid_step()
        if valid_step is not None:
            from bigclam_tpu.resilience import record_resume

            record_resume(getattr(args, "telemetry_dir", None), valid_step)
    sup = _make_supervisor(args, cfg, tel)
    mesh = getattr(model, "mesh", None)
    n_chips = mesh.size if mesh is not None else 1
    with MetricsLogger(args.metrics, echo=not args.quiet) as ml:
        cb = ml.step_callback(
            g.num_directed_edges,
            chips=n_chips,
            path=getattr(model, "engaged_path", ""),
            num_nodes=g.num_nodes,
        )

        def _run_fit():
            if cfg.quality_mode and getattr(args, "device_annealing", False):
                from bigclam_tpu.models.quality import fit_quality_device

                # --checkpoint-dir wires REPAIR-ROUND checkpointing on
                # this path (round 6): a crash mid-repair resumes from
                # the last completed round. Cycle-granularity saves stay
                # a host-loop feature (a full-F fetch per cycle).
                qres = fit_quality_device(
                    model, F0, callback=cb, checkpoints=ckpt, resume=resume
                )
                return qres, qres.fit
            if cfg.quality_mode:
                from bigclam_tpu.models.quality import fit_quality

                qres = fit_quality(
                    model, F0, callback=cb, checkpoints=ckpt, resume=resume
                )
                return qres, qres.fit
            return None, model.fit(
                F0, callback=cb, checkpoints=ckpt, resume=resume
            )

        import time as _time

        t_fit = _time.perf_counter()
        with prof.stage("fit"), trace(args.profile_dir):
            # the supervisor retries transient-classified failures (and
            # stall escalations, when wired to abort): each retried
            # attempt re-enters the fit WITH the CheckpointManager, so a
            # retry resumes instead of restarting
            qres, res = sup.run_fit(_run_fit)
        fit_wall_s = round(_time.perf_counter() - t_fit, 4)
    out = {
        "llh": res.llh,
        "iters": res.num_iters,
        "n": g.num_nodes,
        "edges": g.num_edges,
        "k": cfg.num_communities,
        # representation identity: the perf ledger refuses to baseline a
        # sparse run against a dense one (obs.ledger.match_key), and the
        # bench/ledger rows must say which bytes/edge model applies
        "representation": cfg.representation,
        # resolved edge-kernel path (ISSUE 13): joins the ledger match
        # key so a silent XLA fallback can never baseline against a
        # fused run; the reason says WHY when it is a fallback
        "kernel_path": getattr(model, "engaged_path", ""),
        "kernel_path_reason": getattr(model, "path_reason", ""),
        # node-axis partition identity (ISSUE 16): joins the ledger
        # match key — a 2d run never baselines against a 1d run
        "partition": cfg.partition,
    }
    # 2D neighbor-grad exchange mode (ISSUE 17): the EFFECTIVE mode the
    # trainer resolved (closure only when C>1 and the tables baked) —
    # joins the ledger match key, so closure and dense-psum runs never
    # cross-baseline; absent on 1d models, matching the key's None
    gx = getattr(model, "grad_exchange", None)
    if gx is not None:
        out["grad_exchange"] = gx
    if mesh is not None:
        # execution-shape identity (obs.ledger.match_key, ISSUE 10): a
        # (4,1) run must never baseline against (2,2) — the collective
        # work differs at equal device count
        out["mesh"] = _mesh_label(mesh)
    cm = getattr(model, "comms", None)
    if cm is not None:
        out["comms_bytes_per_step"] = round(cm.bytes_per_step(), 1)
    mem = getattr(model, "memory", None)
    if mem is not None:
        # the static capacity model next to the comms model (obs.memory,
        # ISSUE 12) — same figure the perf ledger verdicts
        out["hbm_modeled_bytes"] = round(mem.hbm_bytes(), 1)
    if cfg.representation == "sparse":
        out["sparse_m"] = getattr(model, "m", cfg.sparse_m)
        if hasattr(model, "comm_mode"):
            out["sparse_comm"] = model.comm_mode
            out["sparse_comm_cap"] = model.comm_cap
    if qres is not None:
        out["quality_cycles"] = qres.num_cycles
        out["quality_total_iters"] = qres.total_iters
        out["cycles_llh"] = [round(v, 2) for v in qres.cycles_llh]
    with prof.stage("extract"):
        com = (
            extraction.extract_communities(res.F, g)
            if (args.out or args.export_gexf)
            else None
        )
        if args.out:
            extraction.save_communities(args.out, com)
            out["communities"] = len(com)
            out["out"] = args.out
        if getattr(args, "publish_dir", None):
            # serving snapshot publication (ISSUE 14): the checkpoint
            # manager's atomic publish/latest API — a running `cli
            # serve --snapshots <dir>` hot-swaps to this fit's F
            from bigclam_tpu.serve.snapshot import publish_snapshot

            from bigclam_tpu.utils.checkpoint import published_step_of

            # fit_wall_s/iters: the full-fit cost baseline `cli refit`
            # prices its refit_cost_ratio against (ISSUE 15)
            pub_meta = {"llh": res.llh, "seed": cfg.seed,
                        "fit_wall_s": fit_wall_s,
                        "fit_iters": res.num_iters}
            shards = int(getattr(args, "publish_shards", 0) or 0)
            if shards > 1:
                # fleet publication (ISSUE 18 tentpole): per-shard
                # row-range archives + a generation manifest, under the
                # same publish-lock monotonicity as single archives. A
                # store-backed fit slices on the store's host ranges
                # (each serving shard then covers whole cache shards —
                # its adjacency loads without touching neighbors);
                # store-less fits take equal row slices
                from bigclam_tpu.serve.snapshot import (
                    publish_fleet_snapshot,
                )

                store = getattr(args, "_store", None)
                ranges = None
                if store is not None:
                    try:
                        ranges = store.host_ranges(shards)
                    except ValueError:
                        pass    # shards does not divide the cache
                if ranges is None:
                    n = g.num_nodes
                    ranges = [
                        (s * n // shards, (s + 1) * n // shards)
                        for s in range(shards)
                    ]
                kw = {}
                if cfg.representation == "sparse":
                    # sparse fits publish M-sized member lists, never a
                    # densified N*K block — re-sparsify the extracted F
                    # (top-M per row; lossless whenever M held the live
                    # support, which the fit's cap guarantees)
                    from bigclam_tpu.ops.sparse_members import from_dense

                    m_pub = int(out.get("sparse_m", cfg.sparse_m))
                    ids_pub, w_pub, _ = from_dense(
                        res.F, m_pub, cfg.num_communities, g.num_nodes
                    )
                    kw = {"ids": ids_pub, "w": w_pub}
                else:
                    kw = {"F": res.F}
                step, path = publish_fleet_snapshot(
                    args.publish_dir,
                    ranges,
                    raw_ids=g.raw_ids,
                    num_edges=g.num_edges,
                    cfg=cfg,
                    meta=pub_meta,
                    **kw,
                )
                out["published"] = path
                out["generation"] = step
                out["publish_shards"] = shards
                if tel is not None:
                    tel.event("fleet_publish", step=step, shards=shards)
            else:
                path = publish_snapshot(
                    args.publish_dir,
                    # step=None: the NEXT generation under the publish
                    # lock (ISSUE 15). Iteration counts made terrible
                    # steps — a re-fit converging in fewer iterations
                    # would publish a "lower" generation the
                    # never-backward pointer rule then rightly refused
                    # to serve
                    step=None,
                    F=res.F,
                    raw_ids=g.raw_ids,
                    num_edges=g.num_edges,
                    cfg=cfg,
                    meta=pub_meta,
                )
                out["published"] = path
                out["generation"] = published_step_of(path)
        if args.save_f:
            np.save(args.save_f, res.F)
            out["save_f"] = args.save_f
        if args.export_gexf:
            from bigclam_tpu.utils.viz import export_gexf

            export_gexf(args.export_gexf, g, communities=com, F=res.F)
            out["export_gexf"] = args.export_gexf
    if getattr(args, "follow", None):
        # the continuous fit->publish->serve loop (ISSUE 15 tentpole):
        # watch a delta directory, and per new edge file run delta
        # re-ingest -> warm-start refit -> publish the next generation
        # (a running `cli serve --watch-snapshots` hot-swaps each one);
        # preconditions were validated up front, before the fit
        store = args._store
        from bigclam_tpu.models.refit import follow_deltas

        with prof.stage("follow"):
            out["follow"] = follow_deltas(
                store, cfg, res.F, args.publish_dir, args.follow,
                halo=getattr(args, "refit_halo", 1),
                max_rounds=getattr(args, "refit_rounds", 12),
                interval_s=getattr(args, "follow_interval", 0.5),
                max_deltas=getattr(args, "follow_max", 0),
                timeout_s=getattr(args, "follow_timeout", None),
                quiet=args.quiet,
            )
    if tel is not None:
        tel.set_final(out)
    print(json.dumps(out))
    return 0


def cmd_sweep(args) -> int:
    tel = _open_telemetry(args, "sweep")
    try:
        return _cmd_sweep(args, tel)
    finally:
        _close_telemetry(tel)


def _cmd_sweep(args, tel=None) -> int:
    from bigclam_tpu.models.model_selection import sweep_k
    from bigclam_tpu.utils.profiling import StageProfile, trace

    prof = StageProfile()
    with prof.stage("graph_load"):
        g, cfg = _build(args, getattr(args, "max_com"))
    if getattr(args, "quality", False):
        cfg = cfg.replace(quality_mode=True)
    if args.checkpoint_dir:
        print(
            "note: checkpointing is per-fit; the sweep records progress in "
            f"{args.checkpoint_dir}/sweep_state.json",
            file=sys.stderr,
        )
    from bigclam_tpu.utils import MetricsLogger

    factory = (
        (lambda c: _make_model(g, c, args))
        if (args.mesh or args.distributed or cfg.representation == "sparse")
        else None
    )
    with MetricsLogger(args.metrics, echo=not args.quiet) as ml:
        def cb(k, llh):
            ml.log({"k": k, "llh": llh})

        with prof.stage("sweep"), trace(args.profile_dir):
            # retried sweep attempts resume from sweep_state.json (per-K
            # journal) + the within-K checkpoints — the K-sweep-position
            # half of preemption-safe auto-resume. --resume never ignores
            # the journal (cold sweep); RETRIES within this run still
            # resume from what the run itself journaled.
            sup = _make_supervisor(args, cfg, tel)
            first_attempt = [True]

            def _run_sweep():
                first, first_attempt[0] = first_attempt[0], False
                return sweep_k(
                    g,
                    cfg,
                    model_factory=factory,
                    callback=cb,
                    state_dir=args.checkpoint_dir,
                    device_annealing=getattr(
                        args, "device_annealing", False
                    ),
                    resume=(
                        getattr(args, "resume", "auto") != "never"
                        or not first
                    ),
                )

            res = sup.run_fit(_run_sweep, site="sweep")
    out = {
        "chosen_k": res.chosen_k,
        "kset": res.kset,
        "llh_by_k": {str(k): v for k, v in res.llh_by_k.items()},
        # workload identity for the perf ledger (obs.ledger.match_key):
        # without n/edges, sweeps over different graphs would baseline
        # against each other. chosen_k is an OUTPUT (noisy across
        # re-runs), so it must not ride the match key — k stays unset
        "n": g.num_nodes,
        "edges": g.num_directed_edges // 2,
        "representation": cfg.representation,
    }
    if args.mesh:
        # the ledger's execution-shape key (ISSUE 10); sweeps build their
        # models per K inside sweep_k, so the flag is the identity here
        out["mesh"] = args.mesh.replace(",", "x")
    if tel is not None:
        tel.set_final(out)
    print(json.dumps(out))
    return 0


def cmd_ingest(args) -> int:
    tel = _open_telemetry(args, "ingest")
    try:
        return _cmd_ingest(args, tel)
    finally:
        _close_telemetry(tel)


def _cmd_ingest(args, tel=None) -> int:
    """Compile a SNAP edge list into a binary shard cache, out of core.

    Deliberately jax-free: ingest runs on data-prep hosts where the only
    budget that matters is host RAM — the reported peak-RSS delta is the
    ingest pipeline's own footprint (O(chunk + bucket + N), not O(file)).
    Telemetry (when on) follows suit: device-memory sampling is disabled
    (_open_telemetry), so the stage events/watermarks never import jax."""
    from bigclam_tpu.graph.store import (
        GraphStore,
        compile_graph_cache,
        is_cache_dir,
    )
    from bigclam_tpu.utils.profiling import IngestProfile

    if getattr(args, "delta", None):
        # delta re-ingest (ISSUE 15): append an edge file to an EXISTING
        # cache, rebuilding only the touched node ranges (jax-free like
        # the rest of this entry; untouched shard blobs byte-identical)
        if not is_cache_dir(args.cache_dir):
            print(
                f"error: --delta needs an existing compiled cache at "
                f"{args.cache_dir} (run a full ingest first)",
                file=sys.stderr,
            )
            return 1
        store = GraphStore.open(args.cache_dir)
        prof = IngestProfile()
        try:
            info = store.apply_delta(
                args.delta, seed_rebake=not args.no_seed_bake,
                profile=prof,
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        out = {
            "cache_dir": args.cache_dir,
            "delta": info["delta_path"],
            "delta_seq": info["delta_seq"],
            "edges_added": info["edges_added"],
            "edges": info["num_directed_edges"] // 2,
            "touched_shards": info["touched_shards"],
            "touched_rows": int(info["touched_rows"].size),
            "touched_frac": info["touched_frac"],
            "phi_rebaked_shards": info["phi_rebaked_shards"],
            "files_read": list(info["files_read"]),
            "seconds": info["seconds"],
            **prof.report(),
        }
        if tel is not None:
            tel.set_final(out)
        print(json.dumps(out))
        return 0

    if not args.graph:
        print(
            "error: a full ingest needs --graph (pass --delta to "
            "append to an existing cache instead)",
            file=sys.stderr,
        )
        return 1
    if is_cache_dir(args.cache_dir) and not args.overwrite:
        print(
            f"{args.cache_dir}: already compiled (use --overwrite to "
            "rebuild, or --delta to append an edge file)",
            file=sys.stderr,
        )
        return 1
    if args.balance:
        # balance pulls in the parallel package (and with it jax); import
        # it BEFORE the profile's RSS baseline so the reported delta
        # measures the streaming build, not the jax import
        import bigclam_tpu.parallel.balance  # noqa: F401

    prof = IngestProfile()
    store = compile_graph_cache(
        args.graph,
        args.cache_dir,
        num_shards=args.shards,
        chunk_bytes=args.chunk_bytes,
        workers=args.workers,
        balance=args.balance,
        overwrite=args.overwrite,
        profile=prof,
        seed_bake=not args.no_seed_bake,
        seed_cap=args.seed_cap,
        seed=args.seed,
        closure_bake=not getattr(args, "no_closure_bake", False),
        closure_cap=max(getattr(args, "closure_cap", 0) or 0, 0),
    )
    out = {
        "cache_dir": args.cache_dir,
        "n": store.num_nodes,
        "edges": store.num_directed_edges // 2,
        "shards": store.num_shards,
        "balanced": store.balanced,
        # from the manifest, not the flag: the work guard can skip an
        # uncapped bake on hub-heavy graphs (store.SEED_BAKE_EXACT_MAX_WORK)
        "seed_baked": store.manifest.get("seed_scores", {}).get(
            "baked", False
        ),
        # 2D closure gather lists (manifest v3, ISSUE 16)
        "closure_baked": store.manifest.get("closure", {}).get(
            "baked", False
        ),
        "chunk_bytes": args.chunk_bytes,
        **prof.report(),
    }
    if tel is not None:
        tel.set_final(out)
    print(json.dumps(out))
    return 0


def cmd_profile(args) -> int:
    tel = _open_telemetry(args, "profile")
    try:
        return _cmd_profile(args, tel)
    finally:
        _close_telemetry(tel)


def _cmd_profile(args, tel=None) -> int:
    """Run N instrumented steps under a jax.profiler capture (ISSUE 6):
    each step wrapped in a StepTraceAnnotation + span, so the captured XLA
    timeline (tensorboard-viewable) aligns with our span names, and the
    per-step timings land in the telemetry/ledger like a fit's would.

        cli profile --graph g.txt --k 100 --steps 20 \\
            --profile-dir prof/ --telemetry-dir run1/
    """
    import os
    import statistics
    import time

    from bigclam_tpu.obs import trace as obs_trace
    from bigclam_tpu.utils import MetricsLogger
    from bigclam_tpu.utils.profiling import StageProfile, trace

    if args.steps < 1:
        # refuse before the expensive graph-load/model-build/warmup work
        # (an empty timing window has no median)
        print("error: profile --steps must be >= 1", file=sys.stderr)
        return 2
    if tel is None:
        print(
            "warning: profile without --telemetry-dir captures step "
            "annotations only — span names need a telemetry run to "
            "attach to, and no per-step timings land anywhere",
            file=sys.stderr,
        )
    prof = StageProfile()
    with prof.stage("graph_load"):
        g, cfg = _build(args, args.k)
    cfg = cfg.replace(max_iters=args.steps, conv_tol=0.0)
    with prof.stage("model_build"):
        model = _make_model(g, cfg, args)
    if tel is not None:
        tel.commit_gate()
    with prof.stage("seeding"):
        F0 = _init_F(g, cfg, args)
    import jax

    pdir = args.profile_dir or (
        os.path.join(args.telemetry_dir, "profile")
        if getattr(args, "telemetry_dir", None)
        else "bigclam_profile"
    )
    mesh = getattr(model, "mesh", None)
    n_chips = mesh.size if mesh is not None else 1
    state = model.init_state(F0)
    with prof.stage("warmup"):
        for _ in range(max(args.warmup, 0)):
            state = model._step(state)
        jax.block_until_ready(state.F)
    times = []
    with MetricsLogger(args.metrics, echo=not args.quiet) as ml:
        cb = ml.step_callback(
            g.num_directed_edges,
            chips=n_chips,
            path=getattr(model, "engaged_path", ""),
            num_nodes=g.num_nodes,
        )
        with prof.stage("profiled_steps"), trace(pdir):
            for i in range(args.steps):
                t0 = time.perf_counter()
                with obs_trace.step_annotation(i), obs_trace.span(
                    "step", emit=False
                ):
                    state = model._step(state)
                    jax.block_until_ready(state.F)
                times.append(time.perf_counter() - t0)
                cb(i, float(state.llh))
    out = {
        "steps": args.steps,
        "warmup": args.warmup,
        "sec_per_step_p50": round(statistics.median(times), 6),
        "sec_per_step_min": round(min(times), 6),
        "profile_dir": pdir,
        "path": getattr(model, "engaged_path", ""),
        "kernel_path": getattr(model, "engaged_path", ""),
        "n": g.num_nodes,
        "edges": g.num_edges,
        "k": cfg.num_communities,
        "representation": cfg.representation,
        # ledger match-key identity (ISSUE 16): the profile/bench entry
        # stamps the partition exactly like fit does
        "partition": cfg.partition,
    }
    gx = getattr(model, "grad_exchange", None)
    if gx is not None:
        out["grad_exchange"] = gx
    if mesh is not None:
        out["mesh"] = _mesh_label(mesh)
    cm = getattr(model, "comms", None)
    if cm is not None:
        out["comms_bytes_per_step"] = round(cm.bytes_per_step(), 1)
    mem = getattr(model, "memory", None)
    if mem is not None:
        out["hbm_modeled_bytes"] = round(mem.hbm_bytes(), 1)
    if tel is not None:
        tel.set_final(out)
    print(json.dumps(out))
    return 0


def cmd_perf(args) -> int:
    """Perf-ledger tooling (obs.ledger): `record` appends a record built
    from a finished telemetry dir, `diff` gates the latest run against its
    matched baseline (exit 2 on regression, 1 on missing data), `show`
    lists recent records."""
    from bigclam_tpu.obs import ledger as L

    if args.action == "record":
        try:
            rec = L.record_from_dir(args.telemetry_dir, note=args.note)
        except (OSError, ValueError) as e:
            # mistyped dir / run that died before finalize: the clean
            # exit-1 contract, not a traceback
            print(f"perf record: {e}", file=sys.stderr)
            return 1
        errors = L.validate_record(rec)
        if errors:
            print(f"invalid record: {errors}", file=sys.stderr)
            return 1
        L.PerfLedger(args.ledger).append(rec)
        print(json.dumps(rec, sort_keys=True))
        return 0

    led = L.PerfLedger(args.ledger)
    recs = led.load()
    if led.load_errors:
        print(
            f"note: {led.load_errors} unparsable ledger line(s) skipped",
            file=sys.stderr,
        )
    if args.action == "show":
        for rec in recs[-args.n:]:
            print(json.dumps(rec, sort_keys=True))
        if not recs:
            print(f"{args.ledger}: no records", file=sys.stderr)
        return 0

    # diff
    if not recs:
        print(f"{args.ledger}: no records to diff", file=sys.stderr)
        return 1
    new = led.latest(recs, run=args.run)
    if new is None:
        print(f"run {args.run!r} not found in {args.ledger}",
              file=sys.stderr)
        return 1
    base = led.baseline_for(new, recs)
    if base is None:
        print(
            f"no matched baseline for run {new.get('run')} "
            f"(entry={new.get('entry')}, cfg={new.get('cfg_digest')}, "
            f"backend={new.get('backend')}, host={new.get('host')})",
            file=sys.stderr,
        )
        return 1
    d = L.diff_records(base, new, tolerance=args.tolerance)
    print(L.render_diff(d))
    return 2 if d["regression"] else 0


def cmd_report(args) -> int:
    """Render a telemetry directory human-readable (obs.report): merged
    per-process run reports, stage seconds, device-memory watermarks,
    compile counts, stalls, model health + anomalies, and an events.jsonl
    schema check. Exit 1 when artifacts are missing/invalid, so CI can
    gate on a telemetry dir. --json emits the machine-readable merge
    (obs.report.render_json) with the SAME exit-code contract.

    --fleet flips DIR from one telemetry dir to a fleet ROOT whose
    immediate subdirectories are member telemetry dirs (the router's
    plus every replica's --telemetry-dir, ISSUE 19): the report merges
    them into one fleet view — router latency/QPS, per-shard rollup
    across replicas, per-hop latency decomposition, generation ages."""
    if getattr(args, "fleet", False):
        if getattr(args, "json", False):
            from bigclam_tpu.obs.report import render_fleet_json

            obj, errors = render_fleet_json(args.dir)
            print(json.dumps(obj, sort_keys=True))
            return 1 if errors else 0
        from bigclam_tpu.obs.report import render_fleet

        text, errors = render_fleet(args.dir)
        print(text)
        if errors:
            print(f"\n{errors} problem(s) found", file=sys.stderr)
        return 1 if errors else 0
    if getattr(args, "json", False):
        from bigclam_tpu.obs.report import render_json

        obj, errors = render_json(args.dir)
        print(json.dumps(obj, sort_keys=True))
        return 1 if errors else 0
    from bigclam_tpu.obs.report import render

    text, errors = render(args.dir)
    print(text)
    if errors:
        print(f"\n{errors} problem(s) found", file=sys.stderr)
    return 1 if errors else 0


def cmd_preflight(args) -> int:
    """Capacity preflight (obs.memory, ISSUE 12): predict per-device
    HBM, per-host RSS, and bytes/step for a config + graph + device
    target WITHOUT touching jax or any hardware — the go/no-go answer
    the pod drill runs before a single chip is reserved.

        cli preflight --graph friendster.cache --k 1000 \\
            --mesh 64,1 --device-kind v5e --store-native

    Graph input: a compiled cache dir (exact manifest numbers, per-
    shard edge counts included) or a SNAP text path (+ --nodes; edges
    estimated from the file size unless --edges). Exit 0 = fits,
    2 = does not fit (the verdict names the binding constraint and the
    knobs that relax it), 1 = bad input."""
    import os

    from bigclam_tpu.graph.store import GraphStore, is_cache_dir
    from bigclam_tpu.obs import memory as M

    shard_counts = None
    closure_pairs = None
    rows_per_shard = 0
    notes: list = []
    if is_cache_dir(args.graph):
        w = GraphStore.open(args.graph).workload()
        n = args.nodes or w["n"]
        directed = 2 * args.edges if args.edges else w["directed_edges"]
        rows_per_shard = w["rows_per_shard"]
        shard_counts = w["shard_edge_counts"]
        # baked closure pair counts (manifest v3): exact 2D closure-
        # exchange pricing instead of the coupon-collector estimate
        cl = w.get("closure") or {}
        if cl.get("baked"):
            closure_pairs = cl.get("pair_counts")
    elif os.path.isfile(args.graph):
        if not args.nodes:
            print(
                "error: a text --graph carries no manifest — pass "
                "--nodes (and ideally --edges), or `cli ingest` it "
                "first and preflight the cache",
                file=sys.stderr,
            )
            return 1
        n = args.nodes
        if args.edges:
            directed = 2 * args.edges
        else:
            # SNAP text: ~13 bytes per "u\tv\n" line, one undirected
            # edge per line -> 2 directed per line
            directed = 2 * max(os.path.getsize(args.graph) // 13, 1)
            notes.append(
                "edge count estimated from file size (~13 B/line); "
                "pass --edges or preflight a compiled cache for exact "
                "numbers"
            )
    else:
        print(f"error: --graph {args.graph}: no such file or cache dir",
              file=sys.stderr)
        return 1

    if getattr(args, "serve", False):
        # serving-fleet pricing (ISSUE 18 satellite): per-replica RSS
        # (sparse-aware snapshot + inverted index + cache + adjacency
        # slice) and fleet QPS capacity vs --qps-target — jax-free,
        # before a single replica process is launched
        host_ram = (
            float(args.host_ram_gb) * (1 << 30)
            if args.host_ram_gb else 0.0
        )
        try:
            p = M.serve_preflight(
                n,
                directed,
                args.k,
                shards=args.serve_shards,
                replicas=args.serve_replicas,
                representation=args.representation,
                sparse_m=args.sparse_m,
                itemsize=8 if args.dtype == "float64" else 4,
                cache_slots=args.serve_cache_slots,
                avg_memberships=args.avg_memberships,
                qps_target=args.qps_target,
                qps_per_replica=args.qps_per_replica,
                host_ram_bytes=host_ram,
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        p["notes"] = notes + p["notes"]
        if args.json:
            print(json.dumps(p, sort_keys=True))
        else:
            print(M.render_serve_preflight(p))
        return 0 if p["fits"] else 2

    if args.mesh:
        dp, tp = (int(x) for x in args.mesh.split(","))
    else:
        dp, tp = max(args.devices, 1), 1
    if closure_pairs is not None and len(closure_pairs) != dp:
        # pair counts are per STORE shard — only exact when the cache
        # shard grid IS the device grid (the 2D trainers require that)
        closure_pairs = None
    if shard_counts:
        # aggregate the cache's per-shard counts into TRAINER shards
        # (dp groups of contiguous store shards). dp == 1 included: the
        # single device then holds EVERY shard's edges — skipping the
        # aggregation would underprice the graph by ~num_shards x
        s = len(shard_counts)
        if s % dp == 0:
            per = s // dp
            shard_counts = [
                sum(shard_counts[i * per : (i + 1) * per])
                for i in range(dp)
            ]
        else:
            notes.append(
                f"cache has {s} shards, not divisible by dp={dp}: "
                "per-shard counts estimated (recompile with --shards "
                f"{dp} for exact geometry)"
            )
            shard_counts = None

    hbm = 0.0
    if args.hbm_bytes:
        hbm = float(args.hbm_bytes)
    elif args.hbm_gb:
        hbm = float(args.hbm_gb) * (1 << 30)
    elif args.device_kind:
        hbm = float(M.DEVICE_HBM_BYTES[args.device_kind])
    host_ram = float(args.host_ram_gb) * (1 << 30) if args.host_ram_gb \
        else 0.0

    from bigclam_tpu.config import BigClamConfig

    try:
        p = M.preflight(
            n,
            directed,
            args.k,
            dp=dp,
            tp=tp,
            itemsize=8 if args.dtype == "float64" else 4,
            num_candidates=args.max_backtracks + 1,
            representation=args.representation,
            sparse_m=args.sparse_m,
            support_every=args.support_every,
            schedule=args.schedule,
            store_native=args.store_native,
            health_every=max(args.health_every or 0, 0),
            edge_chunk=args.edge_chunk or BigClamConfig.edge_chunk,
            shard_edge_counts=shard_counts,
            device_hbm_bytes=hbm,
            host_ram_bytes=host_ram,
            processes=max(args.processes, 1),
            chunk_bytes=args.chunk_bytes,
            csr_block_b=args.csr_block_b,
            rows_per_shard=rows_per_shard,
            partition=getattr(args, "partition", "1d"),
            replica_cols=max(getattr(args, "replica_cols", 1) or 1, 1),
            closure_pair_counts=closure_pairs,
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    p["notes"] = notes + p["notes"]
    if args.json:
        print(json.dumps(p, sort_keys=True))
    else:
        print(M.render_preflight(p))
    return 0 if p["fits"] else 2


def cmd_watch(args) -> int:
    """Live-tail a telemetry directory (obs.watch): LLH / grad-norm /
    churn sparklines from the health events, anomalies, stalls, last-
    write age. Reads events.jsonl only — safe to run from any host while
    the fit is still going; exits when the run finalizes.

    --fleet tails a fleet ROOT instead (ISSUE 19): one row per member
    telemetry dir (router + replicas) with generation age, stalls, and
    the router's slow-trace sparkline; exits when every member ends."""
    if getattr(args, "fleet", False):
        from bigclam_tpu.obs.watch import watch_fleet

        return watch_fleet(
            args.dir,
            interval=args.interval,
            once=args.once,
            width=args.width,
        )
    from bigclam_tpu.obs.watch import watch

    return watch(
        args.dir,
        interval=args.interval,
        once=args.once,
        width=args.width,
    )


def _parse_query_spec(spec: str) -> dict:
    """--query shorthand: 'communities_of:12', 'members_of:3',
    'suggest_for:12' — or a raw JSON object for anything richer
    (explicit-neighbor suggests)."""
    spec = spec.strip()
    if spec.startswith("{"):
        try:
            return json.loads(spec)
        except ValueError as e:
            raise SystemExit(f"error: --query {spec!r}: not JSON ({e})")
    fam, _, arg = spec.partition(":")
    keys = {"communities_of": "u", "members_of": "c", "suggest_for": "u"}
    if fam not in keys:
        raise SystemExit(
            f"error: --query {spec!r}: family must be one of "
            "communities_of/members_of/suggest_for (or pass a JSON object)"
        )
    try:
        return {"family": fam, keys[fam]: int(arg)}
    except ValueError:
        raise SystemExit(
            f"error: --query {spec!r}: {keys[fam]!r} must be an integer "
            f"(got {arg!r})"
        )


def cmd_serve(args) -> int:
    tel = _open_telemetry(args, "serve")
    try:
        return _cmd_serve(args, tel)
    finally:
        _close_telemetry(tel)


def _cmd_serve(args, tel=None) -> int:
    """Membership serving (ISSUE 14): answer the three query families
    from a published snapshot through the request batcher.

        cli serve --snapshots snaps/ --graph g.cache \\
            --query communities_of:12 --query members_of:3
        cli serve --snapshots snaps/ --graph g.cache \\
            --queries load.jsonl --results answers.jsonl \\
            --telemetry-dir run1/ --perf-ledger perf/ledger.jsonl

    Read families (communities_of / members_of) are answered jax-free
    from the snapshot + load-time inverted index; suggest_for runs the
    batched fold-in (jax imported lazily on first use). Prints the
    serving stats JSON (p50/p99 latency, QPS, cache hit rate) and stamps
    it into the telemetry final, so `cli perf diff` verdicts serve p99
    against the run's matched baseline. Exit 1 when any query errored."""
    from bigclam_tpu.graph.store import GraphStore, is_cache_dir
    from bigclam_tpu.serve.server import MembershipServer
    from bigclam_tpu.serve.snapshot import SnapshotError
    from bigclam_tpu.utils.profiling import StageProfile

    if bool(args.snapshots) == bool(getattr(args, "fleet", None)):
        print(
            "error: serve needs exactly one of --snapshots (single-"
            "process) or --fleet (shard-replica mode)",
            file=sys.stderr,
        )
        return 1
    if getattr(args, "fleet", None):
        return _cmd_serve_fleet_replica(args, tel)
    prof = StageProfile()
    store = graph = None
    if args.graph:
        with prof.stage("graph_load"):
            if is_cache_dir(args.graph):
                # ALWAYS read-only (ISSUE 15): a serving replica must
                # never self-heal the cache — with the delta pipeline
                # mutating it live, a crc mismatch here is usually a
                # half-applied delta seen through a stale manifest, and
                # a "heal" would rebuild the PRE-delta blobs over the
                # writer's work. Healing belongs to the writer entries
                # (ingest/fit); the server just retries after the swap.
                store = GraphStore.open(args.graph, self_heal=False)
            else:
                from bigclam_tpu.graph import build_graph

                graph = build_graph(args.graph)
    queries = [_parse_query_spec(s) for s in (args.query or [])]
    if args.queries:
        with open(args.queries) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    queries.append(json.loads(line))
                except ValueError as e:
                    print(
                        f"error: {args.queries}:{lineno}: not JSON ({e})",
                        file=sys.stderr,
                    )
                    return 1
    if not queries:
        print(
            "error: nothing to serve — pass --query and/or --queries",
            file=sys.stderr,
        )
        return 1
    try:
        with prof.stage("snapshot_load"):
            server = MembershipServer(
                args.snapshots,
                store=store,
                graph=graph,
                max_batch=args.max_batch,
                budget_s=args.latency_budget_ms / 1e3,
                cache_slots=args.cache_slots,
                foldin_max_iters=args.foldin_max_iters,
                foldin_conv_tol=args.foldin_conv_tol,
                foldin_max_deg=args.foldin_max_deg,
                watch_interval_s=args.watch_snapshots,
                max_queue_depth=getattr(args, "max_queue_depth", 0),
                shed_wait_s=getattr(args, "shed_wait_ms", 0.0) / 1e3,
            )
    except SnapshotError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if tel is not None:
        tel.commit_gate()
    try:
        with prof.stage("serve"):
            results = []
            for _ in range(max(args.repeat, 1)):
                results = server.run_queries(queries)
        out = server.stats()
        out["snapshots"] = args.snapshots
        if args.results:
            with open(args.results, "w") as f:
                for r in results:
                    f.write(json.dumps(r) + "\n")
            out["results"] = args.results
        elif not args.quiet and len(queries) <= 16:
            # one-shot interactive use: the answers ARE the output
            for r in results:
                print(json.dumps(r))
    finally:
        server.close()
    if tel is not None:
        tel.set_final(out)
    print(json.dumps(out))
    return 1 if out.get("serve_errors") else 0


def _cmd_serve_fleet_replica(args, tel=None) -> int:
    """One shard replica of a serving fleet (ISSUE 18): load this
    shard's rows of the latest fleet generation (`cli fit
    --publish-shards`) and answer the line-framed JSON sub-query
    protocol over TCP until a `stop` op or Ctrl-C.

        cli serve --fleet snaps/ --fleet-shard 0 --listen 127.0.0.1:0 \\
            --graph g.cache --max-queue-depth 256 --shed-wait-ms 50

    `cli route` is the client; N replicas of the same shard bind
    different ports and the router dispatches to the least loaded.
    --watch-snapshots polls for newer fleet generations (the replica
    holds the two newest; the router flips fleet-wide, barrier-free)."""
    from bigclam_tpu.graph.store import GraphStore, is_cache_dir
    from bigclam_tpu.serve.fleet import ReplicaServer, ShardReplica
    from bigclam_tpu.serve.snapshot import SnapshotError

    if not args.listen:
        print("error: --fleet needs --listen HOST:PORT",
              file=sys.stderr)
        return 1
    # supervisor-tagged member id + the crash-loop fault site: firing
    # BEFORE the snapshot load means an injected kill here costs the
    # chaos drill milliseconds per respawn, not a full shard load
    import os as _os

    from bigclam_tpu.resilience.faults import maybe_fire

    member = _os.environ.get("BIGCLAM_FLEET_MEMBER", "")
    maybe_fire(
        "replica.start", member=member, shard=int(args.fleet_shard)
    )
    host, _, port_s = args.listen.rpartition(":")
    try:
        port = int(port_s)
    except ValueError:
        print(
            f"error: --listen {args.listen!r}: port must be an integer",
            file=sys.stderr,
        )
        return 1
    store = None
    if args.graph:
        if not is_cache_dir(args.graph):
            print(
                "error: --fleet replicas need a compiled cache --graph "
                "(suggest_for reads the shard's adjacency range from "
                "the store; text graphs have no ranges)",
                file=sys.stderr,
            )
            return 1
        # read-only, like every serving path (ISSUE 15)
        store = GraphStore.open(args.graph, self_heal=False)
    try:
        replica = ShardReplica(
            args.fleet,
            args.fleet_shard,
            store=store,
            cache_slots=args.cache_slots,
            foldin_max_iters=args.foldin_max_iters,
            foldin_conv_tol=args.foldin_conv_tol,
            foldin_max_deg=args.foldin_max_deg,
            watch_interval_s=args.watch_snapshots,
        )
    except (SnapshotError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    server = ReplicaServer(
        replica,
        host=host or "127.0.0.1",
        port=port,
        max_batch=args.max_batch,
        budget_s=args.latency_budget_ms / 1e3,
        max_queue_depth=getattr(args, "max_queue_depth", 0),
        shed_wait_s=getattr(args, "shed_wait_ms", 0.0) / 1e3,
    )
    # the bound endpoint, printed BEFORE serving starts: the launcher
    # (scripts/fleet_gate.py, an operator script) reads this line to
    # learn the port when --listen ended in :0
    print(
        json.dumps(
            {
                "listening": f"{server.host}:{server.port}",
                "shard": args.fleet_shard,
                "generations": replica.generations,
            }
        ),
        flush=True,
    )
    if tel is not None:
        tel.commit_gate()
    try:
        server.serve_until_stopped()
    except KeyboardInterrupt:
        pass
    out = replica.status()
    out["shed"] = server._batcher.shed
    out["depth_peak"] = server._batcher.depth_peak
    server.close()
    if tel is not None:
        tel.set_final(out)
    print(json.dumps(out))
    return 1 if out.get("errors") else 0


def cmd_route(args) -> int:
    tel = _open_telemetry(args, "route")
    try:
        return _cmd_route(args, tel)
    finally:
        _close_telemetry(tel)


def _parse_endpoints(spec: str, timeout_s: float):
    """--endpoints 'host:port,host:port,...' -> TcpReplica transports."""
    from bigclam_tpu.serve.router import TcpReplica

    out = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        host, _, port_s = item.rpartition(":")
        try:
            port = int(port_s)
        except ValueError:
            raise SystemExit(
                f"error: --endpoints {item!r}: expected HOST:PORT"
            )
        out.append(
            TcpReplica(host or "127.0.0.1", port, timeout_s=timeout_s)
        )
    if not out:
        raise SystemExit("error: --endpoints named no endpoints")
    return out


def _cmd_route(args, tel=None) -> int:
    """jax-free fleet query router (ISSUE 18): route the same three
    query families over a sharded replica fleet.

        cli route --fleet snaps/ \\
            --endpoints 127.0.0.1:7001,127.0.0.1:7002 \\
            --queries load.jsonl --results answers.jsonl

    communities_of / suggest_for go to their node's shard (least-loaded
    healthy replica); members_of scatter-gathers every shard and merges
    under the sorted-by-raw-id contract. Every query is pinned to the
    fleet-wide serving generation (the max generation EVERY healthy
    replica of EVERY shard holds) — a mid-stream publication flips the
    whole fleet at once, never a mixed answer. Stats carry the same
    serve_* keys as `cli serve` plus per-shard latency tables, so the
    perf ledger and `cli perf diff` verdict them with one code path.
    --stop sends a stop op to every endpoint instead (fleet teardown).

    Self-healing (ISSUE 20): --members watches a supervisor-published
    membership file instead of a frozen --endpoints list; --daemon
    serves route() itself over the replica wire (long-lived tier);
    --deadline-s / --retry-rounds / --hedge are the per-query failure
    budget (DESIGN.md "Fleet failure model")."""
    from bigclam_tpu.serve.router import FleetRouter, RouterError

    members = getattr(args, "members", None)
    endpoints = []
    if args.endpoints:
        endpoints = _parse_endpoints(
            args.endpoints, args.request_timeout_s
        )
    elif not members:
        print(
            "error: route needs --endpoints and/or --members",
            file=sys.stderr,
        )
        return 1
    if args.stop:
        # teardown is idempotent: an endpoint that is ALREADY gone is a
        # success for the operator's goal — note it, keep tearing down
        # the survivors, exit 0 (ISSUE 20 satellite)
        stopped = 0
        already_down = 0
        for t in endpoints:
            try:
                t.request({"family": "stop"})
                stopped += 1
            except Exception as e:   # noqa: BLE001 — best-effort stop
                already_down += 1
                print(
                    f"note: {t.host}:{t.port}: already down ({e})",
                    file=sys.stderr,
                )
            t.close()
        print(
            json.dumps(
                {
                    "stopped": stopped,
                    "already_down": already_down,
                    "of": len(endpoints),
                }
            )
        )
        return 0
    queries = [_parse_query_spec(s) for s in (args.query or [])]
    if args.queries:
        with open(args.queries) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    queries.append(json.loads(line))
                except ValueError as e:
                    print(
                        f"error: {args.queries}:{lineno}: not JSON "
                        f"({e})",
                        file=sys.stderr,
                    )
                    return 1
    daemon = getattr(args, "daemon", False)
    if not queries and not daemon:
        print(
            "error: nothing to route — pass --query and/or --queries "
            "(or --daemon, or --stop)",
            file=sys.stderr,
        )
        return 1
    import time as _time

    def _mk_router():
        return FleetRouter(
            args.fleet,
            endpoints,
            max_workers=args.max_workers,
            health_interval_s=args.health_interval_s,
            request_timeout_s=args.request_timeout_s,
            deadline_s=getattr(args, "deadline_s", 0.0),
            retry_rounds=getattr(args, "retry_rounds", 1),
            hedge=getattr(args, "hedge", False),
            hedge_delay_s=getattr(args, "hedge_delay_s", 0.0),
            hedge_min_samples=getattr(args, "hedge_min_samples", 64),
            members_file=members,
        )

    router = None
    wait_deadline = _time.monotonic() + max(
        getattr(args, "wait_fleet_s", 0.0), 0.0
    )
    while router is None:
        try:
            router = _mk_router()
        except RouterError as e:
            # with a membership file the fleet may still be COMING UP
            # (supervisor spawning replicas): bounded patience instead
            # of a start-order race
            if members and _time.monotonic() < wait_deadline:
                _time.sleep(0.25)
                continue
            print(f"error: {e}", file=sys.stderr)
            for t in endpoints:
                t.close()
            return 1
    if tel is not None:
        # the stall heartbeat runs ON the router process (ISSUE 19
        # satellite): stall events embed the in-flight trace registry —
        # open trace count + oldest in-flight query age — so a wedged
        # replica hop is attributable from the stall line alone
        tel.open_traces = router.open_trace_count
        tel.oldest_inflight_s = router.oldest_inflight_s
        tel.commit_gate()
    if daemon:
        from bigclam_tpu.serve.router import RouterServer

        lhost, _, lport_s = (
            getattr(args, "listen", None) or "127.0.0.1:0"
        ).rpartition(":")
        try:
            lport = int(lport_s)
        except ValueError:
            print(
                f"error: --listen {args.listen!r}: port must be an "
                "integer",
                file=sys.stderr,
            )
            router.close()
            return 1
        server = RouterServer(
            router, host=lhost or "127.0.0.1", port=lport
        )
        # the bound endpoint, printed BEFORE serving (same contract as
        # the replica hello line: launchers read it for a :0 port)
        print(
            json.dumps(
                {
                    "routing": f"{server.host}:{server.port}",
                    "fleet": args.fleet,
                }
            ),
            flush=True,
        )
        try:
            server.serve_until_stopped()
        except KeyboardInterrupt:
            server.close()
        out = router.stats()
        out["fleet"] = args.fleet
        if tel is not None:
            tel.set_final(out)
        print(json.dumps(out))
        return 0
    try:
        results = []
        for _ in range(max(args.repeat, 1)):
            results = router.run_queries(queries)
        out = router.stats()
        out["fleet"] = args.fleet
        if args.results:
            with open(args.results, "w") as f:
                for r in results:
                    f.write(json.dumps(r) + "\n")
            out["results"] = args.results
        elif not args.quiet and len(queries) <= 16:
            for r in results:
                print(json.dumps(r))
    finally:
        router.close()
    if tel is not None:
        tel.set_final(out)
    print(json.dumps(out))
    return 1 if out.get("serve_errors") else 0


def cmd_fleet(args) -> int:
    tel = _open_telemetry(args, "fleet")
    try:
        return _cmd_fleet(args, tel)
    finally:
        _close_telemetry(tel)


def _cmd_fleet(args, tel=None) -> int:
    """Self-healing fleet supervisor (ISSUE 20): own the replica
    processes of a serving fleet — restart-on-exit with RetryPolicy
    backoff, crash-loop quarantine, elastic membership published to a
    watched members file the router reconciles.

        cli fleet up --fleet snaps/ --shards 2 --replicas 2 \\
            --members members.json
        cli fleet status --control 127.0.0.1:4444
        cli fleet add-replica --control ... --shard 0
        cli fleet drain --control ... --member s0r1
        cli fleet down --control ...

    `up` prints a hello line with the control endpoint + members path,
    then parks until a `down` op (or Ctrl-C). Everything is jax-free."""
    import os as _os

    from bigclam_tpu.resilience.retry import RetryPolicy
    from bigclam_tpu.serve.supervise import FleetSupervisor, control_op

    if args.action == "up":
        if not args.fleet:
            print("error: fleet up needs --fleet DIR", file=sys.stderr)
            return 1
        members = args.members or _os.path.join(
            args.fleet, "members.json"
        )
        replica_args = []
        if args.replica_args:
            import shlex

            replica_args = shlex.split(args.replica_args)
        sup = FleetSupervisor(
            args.fleet,
            members,
            shards=args.shards,
            replicas=args.replicas,
            host=args.host,
            control_port=args.control_port,
            policy=RetryPolicy(
                base_s=args.restart_base_s,
                max_s=args.restart_max_s,
                seed=args.seed,
            ),
            quarantine_after=args.quarantine_after,
            stable_s=args.stable_s,
            drain_grace_s=args.drain_grace_s,
            replica_args=replica_args,
            graph=args.graph,
            watch_snapshots_s=args.watch_snapshots,
            log_dir=args.log_dir,
            seed=args.seed,
        )
        sup.up()
        all_up = sup.wait_all_up(timeout=args.up_timeout_s)
        st = sup.status()
        # the launcher contract (like the replica hello line): control
        # endpoint + members path on stdout BEFORE parking
        print(
            json.dumps(
                {
                    "control": st["control"],
                    "members": members,
                    "all_up": all_up,
                    "fleet_members": [
                        m["id"] for m in st["members"]
                    ],
                }
            ),
            flush=True,
        )
        if tel is not None:
            tel.commit_gate()
        try:
            sup.wait_down()
        except KeyboardInterrupt:
            sup.down()
        st = sup.status()
        out = {
            "replica_restarts": st["replica_restarts"],
            "quarantined": st["quarantined"],
            "fleet_members": {
                m["id"]: {
                    "state": m["state"],
                    "shard": m["shard"],
                    "restarts": m["restarts"],
                }
                for m in st["members"]
            },
        }
        if tel is not None:
            tel.set_final(out)
        print(json.dumps(out))
        return 0
    if args.action == "status" and not args.control and args.members:
        # offline roster: read the membership file directly (works even
        # with the supervisor gone)
        try:
            with open(args.members) as f:
                print(json.dumps(json.load(f)))
        except (OSError, ValueError) as e:
            print(f"error: {args.members}: {e}", file=sys.stderr)
            return 1
        return 0
    if not args.control:
        print(
            f"error: fleet {args.action} needs --control HOST:PORT "
            "(printed by `fleet up`)",
            file=sys.stderr,
        )
        return 1
    op = {"op": args.action.replace("-", "_")}
    if args.action == "add-replica":
        op["shard"] = int(args.shard)
    if args.action == "drain":
        if not args.member:
            print(
                "error: fleet drain needs --member ID", file=sys.stderr
            )
            return 1
        op["member"] = args.member
    try:
        res = control_op(args.control, op)
    except (OSError, ValueError, ConnectionError) as e:
        print(f"error: control {args.control}: {e}", file=sys.stderr)
        return 1
    print(json.dumps(res))
    if args.action == "drain" and not res.get("ok"):
        return 1
    return 0


def cmd_refit(args) -> int:
    tel = _open_telemetry(args, "refit")
    try:
        return _cmd_refit(args, tel)
    finally:
        _close_telemetry(tel)


def _cmd_refit(args, tel=None) -> int:
    """Warm-start incremental refit (ISSUE 15 tentpole part b): start
    from the previous PUBLISHED F, re-optimize only the rows a delta
    touched (plus a halo of their neighbors) with the batched fold-in
    operator, and publish the result as the next snapshot generation.

        cli ingest --delta day2.txt --cache-dir g.cache
        cli refit --graph g.cache --snapshots snaps/ --delta day2.txt

    The PR 8 health detectors watch the restricted objective: divergence
    or plateau-before-tol marks accumulated drift and ESCALATES to a
    full fit (--escalate never publishes the refit F regardless). The
    refit_cost_ratio (refit wall vs the snapshot's recorded full-fit
    wall) and touched_frac land in the telemetry final, the perf ledger
    records them, and `cli perf diff` VERDICTS both."""
    import os

    from bigclam_tpu.models.refit import (
        touched_rows_from_delta,
        warm_start_refit,
    )
    from bigclam_tpu.serve.snapshot import (
        ServingSnapshot,
        SnapshotError,
        publish_snapshot,
    )
    from bigclam_tpu.utils.profiling import StageProfile

    if args.mesh or args.distributed or getattr(
        args, "store_native", False
    ):
        raise SystemExit(
            "error: refit is single-chip for now (the sharded refit "
            "rides the ROADMAP item 1 pod drill) — drop --mesh/"
            "--distributed/--store-native"
        )
    prof = StageProfile()
    try:
        with prof.stage("snapshot_load"):
            snap = ServingSnapshot.load(args.snapshots)
    except SnapshotError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    k = args.k or snap.k
    if k != snap.k:
        raise SystemExit(
            f"error: --k {k} does not match the published snapshot's "
            f"k={snap.k} (a refit continues the same model)"
        )
    with prof.stage("graph_load"):
        g, cfg = _build(args, k)
    if g.num_nodes != snap.n:
        raise SystemExit(
            f"error: graph has {g.num_nodes} nodes but the snapshot "
            f"was published for {snap.n} — wrong graph/snapshot pair "
            "(deltas never grow N; re-ingest + full fit for new nodes)"
        )
    with prof.stage("model_build"):
        model = _make_model(g, cfg, args)
    if tel is not None:
        tel.commit_gate()
    if snap.representation == "sparse":
        from bigclam_tpu.ops.sparse_members import to_dense

        F_prev = to_dense(snap.ids, snap.w, snap.n, snap.k)
    else:
        F_prev = np.asarray(snap.F[: snap.n, : snap.k], np.float64)
    with prof.stage("touched"):
        touched = touched_rows_from_delta(g.raw_ids, args.delta)
    with prof.stage("refit"):
        res = warm_start_refit(
            model, F_prev, touched,
            halo=args.halo,
            max_rounds=args.refit_rounds,
            batch=args.refit_batch,
            foldin_max_iters=args.foldin_max_iters,
            conv_tol=cfg.conv_tol,
        )
    F_final = res.F
    total_wall = res.wall_s
    full_llh = None
    escalated_full = False
    if res.escalated and args.escalate == "full":
        print(
            f"[bigclam] refit escalated "
            f"({[a['check'] for a in res.anomalies]}): running a full "
            "fit warm-started from the refit F",
            file=sys.stderr,
        )
        import time as _time

        t0 = _time.perf_counter()
        with prof.stage("full_fit"):
            full = model.fit(res.F)
        total_wall = round(total_wall + _time.perf_counter() - t0, 4)
        F_final = full.F
        full_llh = full.llh
        escalated_full = True
    base_wall = snap.meta.get("fit_wall_s")
    ratio = (
        round(total_wall / float(base_wall), 6)
        if isinstance(base_wall, (int, float)) and base_wall and not (
            isinstance(base_wall, bool)
        )
        else None
    )
    out = {
        "n": g.num_nodes,
        "edges": g.num_edges,
        "k": k,
        "representation": cfg.representation,
        "from_generation": int(snap.step),
        "touched": res.touched,
        "refit_nodes": res.refit_nodes,
        "touched_frac": res.touched_frac,
        "halo": res.halo,
        "rounds": res.rounds,
        "foldin_iters": res.foldin_iters,
        "converged": res.converged,
        "escalated": res.escalated,
        "escalated_full_fit": escalated_full,
        "refit_wall_s": total_wall,
        "baseline_fit_wall_s": base_wall,
        "refit_cost_ratio": ratio,
        "restricted_llh": res.llh,
        # resolved edge-kernel path (ISSUE 17 backfill): refit records
        # were the one entry missing the ISSUE 13 stamp — without it a
        # refit whose kernels fell back to XLA could baseline against a
        # fused refit in the perf ledger
        "kernel_path": getattr(model, "engaged_path", ""),
        "kernel_path_reason": getattr(model, "path_reason", ""),
    }
    if full_llh is not None:
        out["llh"] = full_llh
    if not args.no_publish:
        with prof.stage("publish"):
            path = publish_snapshot(
                args.snapshots, step=None, F=F_final,
                raw_ids=g.raw_ids, num_edges=g.num_edges, cfg=cfg,
                meta={
                    "refit": True,
                    "seed": cfg.seed,
                    # the full-fit cost baseline propagates through
                    # refit generations so cost ratios keep meaning
                    # "vs a from-scratch fit", not "vs the last refit"
                    "fit_wall_s": base_wall,
                    "touched_frac": res.touched_frac,
                    "refit_rounds": res.rounds,
                    **({"llh": full_llh} if full_llh is not None
                       else {}),
                },
            )
        from bigclam_tpu.utils.checkpoint import published_step_of

        out["published"] = path
        out["generation"] = published_step_of(path) if path else None
    if tel is not None:
        tel.set_final(out)
    print(json.dumps(out))
    return 0


def cmd_eval(args) -> int:
    from bigclam_tpu.evaluation import avg_f1, overlapping_nmi
    from bigclam_tpu.ops.extraction import load_communities

    pred = load_communities(args.pred)
    truth = load_communities(args.truth)
    out = {"f1": avg_f1(pred, truth)}
    if not args.no_nmi:
        out["nmi"] = overlapping_nmi(pred, truth)
    print(json.dumps(out))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="bigclam_tpu", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_fit = sub.add_parser("fit", help="train at a fixed K and extract communities")
    _add_common(p_fit)
    p_fit.add_argument("--k", type=int, default=100)
    p_fit.add_argument(
        "--quality", action="store_true",
        help="quality mode (NOT reference semantics): noise-floor init + "
             "restart annealing — recovers community structure at large K "
             "where the faithful dynamics freeze all-zero rows "
             "(models/quality.py)",
    )
    p_fit.add_argument(
        "--init-noise", type=float, default=None,
        help="noise-kick scale (default: auto, "
             "min(0.02, 4*(avg_degree+1)/N) — see config.init_noise)",
    )
    # None = keep the config.py default (single source of truth)
    p_fit.add_argument("--restart-cycles", type=int, default=None)
    p_fit.add_argument("--restart-tol", type=float, default=None)
    p_fit.add_argument(
        "--quality-max-p", type=float, default=None,
        help="pin the annealing-cycle MAX_P_ clip (default: auto, "
             "1 - avg_deg/(16 N) — see config.quality_max_p)",
    )
    p_fit.add_argument(
        "--seed-exclusion", type=int, choices=(0, 1), default=None,
        help="coverage-aware seed selection (default: auto, on iff "
             "--quality; see config.seed_exclusion)",
    )
    p_fit.add_argument(
        "--device-annealing", action="store_true",
        help="with --quality: keep the annealing schedule device-resident "
             "(models.quality.fit_quality_device — no per-cycle host F "
             "round trip; pod-scale). The quality_repair stage still runs "
             "host-side on the final fetched F",
    )
    p_fit.add_argument("--out", default=None, help="write SNAP cmty file")
    p_fit.add_argument(
        "--publish-dir", default=None,
        help="publish the final F as a serving snapshot (atomic "
             "fsync-rename + crc sidecar + latest.json pointer, "
             "utils.checkpoint.publish): `cli serve --snapshots <dir>` "
             "loads it, and a running server hot-swaps to it",
    )
    p_fit.add_argument(
        "--publish-shards", type=int, default=0, metavar="S",
        help="with --publish-dir: publish the generation as S per-shard "
             "row-range archives + a fleet manifest (ISSUE 18) instead "
             "of one whole-F archive — `cli serve --fleet <dir> "
             "--fleet-shard s` replicas and `cli route` consume it; "
             "store-backed fits slice on the cache's host ranges when S "
             "divides the shard count (0/1 = single archive)",
    )
    p_fit.add_argument("--save-f", default=None, help="write F as .npy")
    p_fit.add_argument(
        "--export-gexf", default=None,
        help="write a Gephi-compatible GEXF with community attributes",
    )
    p_fit.add_argument(
        "--follow", default=None, metavar="DELTA_DIR",
        help="after the fit + publish, watch this directory for new "
             "edge-delta files and run the continuous loop per file: "
             "delta re-ingest (touched shard ranges only) -> warm-start "
             "refit -> publish the next snapshot generation (ISSUE 15; "
             "needs a cache --graph and --publish-dir; a running `cli "
             "serve --watch-snapshots` hot-swaps each generation)",
    )
    p_fit.add_argument(
        "--follow-max", type=int, default=0,
        help="stop after this many deltas (0 = keep watching)",
    )
    p_fit.add_argument(
        "--follow-interval", type=float, default=0.5,
        help="seconds between delta-directory polls",
    )
    p_fit.add_argument(
        "--follow-timeout", type=float, default=None,
        help="exit when no new delta arrives for this many seconds "
             "(default: watch forever)",
    )
    p_fit.add_argument(
        "--refit-halo", type=int, default=1,
        help="--follow refits touched rows plus this many hops of "
             "neighbors (0 = strictly touched rows)",
    )
    p_fit.add_argument(
        "--refit-rounds", type=int, default=12,
        help="--follow block-coordinate sweep cap per delta (health "
             "detectors may escalate to a full fit earlier)",
    )
    p_fit.set_defaults(fn=cmd_fit)

    p_sweep = sub.add_parser("sweep", help="automatic K selection over a log grid")
    _add_common(p_sweep)
    p_sweep.add_argument("--min-com", type=int, default=1000)
    p_sweep.add_argument("--max-com", type=int, default=9000)
    p_sweep.add_argument("--div-com", type=int, default=100)
    p_sweep.add_argument("--ksweep-tol", type=float, default=1e-3)
    p_sweep.add_argument(
        "--quality", action="store_true",
        help="train each K with the quality-mode annealing schedule "
             "(models/quality.py; NOT reference semantics)",
    )
    p_sweep.add_argument(
        "--device-annealing", action="store_true",
        help="with --quality: device-resident annealing per K "
             "(fit_quality_device; no per-cycle host F round trips)",
    )
    p_sweep.set_defaults(fn=cmd_sweep)

    p_ing = sub.add_parser(
        "ingest",
        help="compile a SNAP edge list into a binary graph-shard cache "
             "(streaming, memory-bounded; reports edges/sec + peak RSS)",
    )
    p_ing.add_argument(
        "--graph", default=None,
        help="SNAP edge-list path (required for a full compile; "
             "ignored with --delta, which appends to an existing cache)",
    )
    p_ing.add_argument("--cache-dir", required=True)
    p_ing.add_argument(
        "--delta", default=None, metavar="EDGE_FILE",
        help="append this edge file to the EXISTING --cache-dir by "
             "rebuilding only the touched node ranges (ISSUE 15: "
             "untouched shard blobs stay byte-identical, seed scores "
             "re-bake for touched shards only, manifest bumps "
             "delta_seq; new node ids refuse — re-run a full ingest). "
             "jax-free like the rest of this entry",
    )
    p_ing.add_argument(
        "--shards", type=int, default=8,
        help="node-range shards (match the target mesh's node-shard count "
             "for per-host loading)",
    )
    p_ing.add_argument(
        "--chunk-bytes", type=int, default=64 << 20,
        help="streaming parse chunk size — the host-RSS budget knob",
    )
    p_ing.add_argument(
        "--workers", type=int, default=0,
        help="parallel parse workers (spawn pool; 0 = in-process)",
    )
    p_ing.add_argument(
        "--balance", action="store_true",
        help="bake the degree-balance permutation (parallel/balance.py) "
             "into the shards, so multi-host loads are pre-balanced",
    )
    p_ing.add_argument(
        "--no-seed-bake", action="store_true",
        help="skip baking per-node conductance seed scores into the cache "
             "(default: bake — fit-time seeding on the cache then reads "
             "scores instead of re-streaming the graph)",
    )
    p_ing.add_argument(
        "--seed-cap", type=int, default=None,
        help="degree cap for the baked conductance scorer (the exact "
             "triangle pass is edge-quadratic on hubs; same splitmix64 "
             "estimator as --seeding-degree-cap, exact when cap >= max "
             "degree)",
    )
    p_ing.add_argument(
        "--seed", type=int, default=0,
        help="PRNG seed the capped scorer's sample stream derives from "
             "(match the fit's --seed for identical rankings)",
    )
    p_ing.add_argument(
        "--no-closure-bake", action="store_true",
        help="skip baking the per-shard-pair closure gather lists "
             "(manifest v3; default: bake — the 2D trainers then load "
             "exact touched-dst-row lists instead of streaming them "
             "from the CSR at build time)",
    )
    p_ing.add_argument(
        "--closure-cap", type=int, default=0,
        help="rows per (requester, contributor) closure list before the "
             "pair degrades to the full dst block (0 = uncapped); the "
             "2D all_to_all buffer scales with the BAKED cap",
    )
    p_ing.add_argument("--overwrite", action="store_true")
    p_ing.add_argument(
        "--telemetry-dir", default=None,
        help="run-telemetry directory (events.jsonl + run_report.json; "
             "jax-free on this entry — no device sampling)",
    )
    p_ing.add_argument(
        "--heartbeat-s", type=float, default=300.0,
        help="stall-heartbeat deadline with --telemetry-dir (0 disables)",
    )
    p_ing.add_argument("--quiet", action="store_true")
    p_ing.set_defaults(fn=cmd_ingest)

    p_prof = sub.add_parser(
        "profile",
        help="run N instrumented steps under a jax.profiler capture: the "
             "dump's TraceMe timeline carries the span names (obs.trace), "
             "per-step timings land in --telemetry-dir / the perf ledger",
    )
    _add_common(p_prof)
    p_prof.add_argument("--k", type=int, default=100)
    p_prof.add_argument(
        "--steps", type=int, default=20,
        help="profiled steps (after --warmup un-captured steps)",
    )
    p_prof.add_argument("--warmup", type=int, default=2)
    p_prof.set_defaults(fn=cmd_profile)

    p_perf = sub.add_parser(
        "perf",
        help="perf-regression ledger: record a run, diff the latest run "
             "against its matched baseline (nonzero exit on regression), "
             "or show recent records",
    )
    perf_sub = p_perf.add_subparsers(dest="action", required=True)
    pp_rec = perf_sub.add_parser(
        "record",
        help="append a record built from a finished --telemetry-dir",
    )
    pp_rec.add_argument("--telemetry-dir", required=True)
    pp_rec.add_argument("--ledger", default="perf/ledger.jsonl")
    pp_rec.add_argument("--note", default="")
    pp_diff = perf_sub.add_parser(
        "diff",
        help="latest run vs its matched baseline (same entry/config/"
             "backend/device/host) with noise bands; exit 2 on regression",
    )
    pp_diff.add_argument("--ledger", default="perf/ledger.jsonl")
    pp_diff.add_argument(
        "--tolerance", type=float, default=0.25,
        help="minimum relative noise band (the run's own p50->p90 spread "
             "widens it)",
    )
    pp_diff.add_argument(
        "--run", default=None,
        help="diff this run id instead of the ledger's last record",
    )
    pp_show = perf_sub.add_parser("show", help="print recent records")
    pp_show.add_argument("--ledger", default="perf/ledger.jsonl")
    pp_show.add_argument("-n", type=int, default=10)
    p_perf.set_defaults(fn=cmd_perf)

    p_rep = sub.add_parser(
        "report",
        help="render a --telemetry-dir human-readable (stage seconds, "
             "memory watermarks, compile counts, stalls; validates the "
             "event schema)",
    )
    p_rep.add_argument("dir", help="telemetry directory of a finished run")
    p_rep.add_argument(
        "--json", action="store_true",
        help="machine-readable output (merged reports + events summary + "
             "health/anomalies + recovery) for CI; exit codes unchanged",
    )
    p_rep.add_argument(
        "--fleet", action="store_true",
        help="treat DIR as a fleet root whose subdirectories are member "
             "telemetry dirs (router + replicas); merge them into one "
             "fleet view (per-shard latency/QPS rollup, per-hop "
             "decomposition, generation ages)",
    )
    p_rep.set_defaults(fn=cmd_report)

    p_watch = sub.add_parser(
        "watch",
        help="live-tail a telemetry dir: LLH/grad-norm/churn sparklines "
             "from health events, anomalies, stalls (reads events.jsonl "
             "only; exits when the run finalizes)",
    )
    p_watch.add_argument("dir", help="telemetry directory of a running run")
    p_watch.add_argument(
        "--interval", type=float, default=2.0,
        help="seconds between refreshes",
    )
    p_watch.add_argument(
        "--once", action="store_true",
        help="render one frame and exit (CI / piped use)",
    )
    p_watch.add_argument("--width", type=int, default=48,
                         help="sparkline width in samples")
    p_watch.add_argument(
        "--fleet", action="store_true",
        help="treat DIR as a fleet root (subdirectories = member "
             "telemetry dirs): one row per member with generation age "
             "and stalls, plus the router's slow-trace sparkline; exits "
             "when every member finalizes",
    )
    p_watch.set_defaults(fn=cmd_watch)

    p_srv = sub.add_parser(
        "serve",
        help="answer membership queries from a published F snapshot "
             "(ISSUE 14): communities_of / members_of / suggest_for "
             "(fold-in) through a latency-budgeted request batcher, with "
             "hot-swap to newly published snapshots; read families are "
             "jax-free",
    )
    p_srv.add_argument(
        "--snapshots", default=None,
        help="snapshot directory (`cli fit --publish-dir` / "
             "utils.checkpoint.publish): the latest published snapshot "
             "is served, falling back past corrupt ones (XOR --fleet)",
    )
    p_srv.add_argument(
        "--fleet", default=None, metavar="DIR",
        help="fleet-replica mode (ISSUE 18): serve ONE shard of a "
             "fleet publication (`cli fit --publish-shards`) over TCP "
             "line-framed JSON — needs --fleet-shard and --listen; "
             "`cli route` is the client",
    )
    p_srv.add_argument(
        "--fleet-shard", type=int, default=0, metavar="S",
        help="which shard of the fleet manifest this replica serves",
    )
    p_srv.add_argument(
        "--listen", default=None, metavar="HOST:PORT",
        help="bind address for --fleet replica mode (port 0 picks a "
             "free port; the chosen endpoint is printed as JSON on "
             "stdout before serving starts)",
    )
    p_srv.add_argument(
        "--max-queue-depth", type=int, default=0,
        help="admission control: reject new queries with a fast "
             "'overloaded' error once the batcher queue holds this many "
             "requests (0 = unbounded; sheds are counted, not errors)",
    )
    p_srv.add_argument(
        "--shed-wait-ms", type=float, default=0.0,
        help="admission control: shed queued queries that waited "
             "longer than this before their batch flushed (0 = never; "
             "bounds worst-case latency under overload)",
    )
    p_srv.add_argument(
        "--graph", default=None,
        help="graph-cache dir (preferred: manifest-verified against the "
             "snapshot) or SNAP text path — the adjacency suggest_for "
             "needs for graph nodes; read-only queries work without it",
    )
    p_srv.add_argument(
        "--query", action="append", default=None, metavar="FAMILY:ARG",
        help="one query: communities_of:<u>, members_of:<c>, "
             "suggest_for:<u>, or a JSON object (repeatable)",
    )
    p_srv.add_argument(
        "--queries", default=None,
        help="JSONL file of query objects (one per line) — the load-"
             "test path (scripts/serve_gate.py generates Zipf mixes)",
    )
    p_srv.add_argument(
        "--results", default=None,
        help="write one JSON answer per query line here (default: "
             "answers echo to stdout only for <= 16 queries)",
    )
    p_srv.add_argument(
        "--repeat", type=int, default=1,
        help="run the query set this many times (load testing; stats "
             "accumulate, results keep the last pass)",
    )
    p_srv.add_argument(
        "--latency-budget-ms", type=float, default=5.0,
        help="request-batcher window: a lone query waits at most this "
             "long for batch-mates (the p99 knob)",
    )
    p_srv.add_argument(
        "--max-batch", type=int, default=64,
        help="flush a batch at this many requests even inside the window",
    )
    p_srv.add_argument(
        "--cache-slots", type=int, default=64,
        help="hot-community cache capacity (members_of): admission is "
             "keyed by community mass share — the Zipf head stays "
             "resident (0 disables)",
    )
    p_srv.add_argument(
        "--foldin-max-iters", type=int, default=200,
        help="fold-in row-ascent iteration cap per suggest query",
    )
    p_srv.add_argument(
        "--foldin-conv-tol", type=float, default=None,
        help="per-node fold-in convergence tolerance (default: the "
             "trainer's conv_tol from the snapshot config)",
    )
    p_srv.add_argument(
        "--foldin-max-deg", type=int, default=4096,
        help="neighbor cap per suggest query (hub truncation; counted "
             "in the stats when it engages)",
    )
    p_srv.add_argument(
        "--watch-snapshots", type=float, default=0.0,
        help="poll the snapshot dir every this many seconds and "
             "hot-swap when a newer snapshot is published (0 = off; "
             "swaps drain in-flight batches and drop no queries)",
    )
    p_srv.add_argument(
        "--telemetry-dir", default=None,
        help="run-telemetry directory: per-batch `serve` events + "
             "snapshot_swap events + the final serving stats (render "
             "with `cli report`; jax-free on this entry)",
    )
    p_srv.add_argument(
        "--heartbeat-s", type=float, default=300.0,
        help="stall-heartbeat deadline with --telemetry-dir (0 disables)",
    )
    p_srv.add_argument(
        "--perf-ledger", default=None,
        help="append this serve run's record (serve p99/QPS/cache hit "
             "rate) to a perf-ledger JSONL; `cli perf diff` then "
             "VERDICTS serve p99 against the matched serve baseline",
    )
    # note: serve has no self-heal knob — a serving replica opens the
    # cache READ-ONLY (a heal racing the delta pipeline would rebuild
    # pre-delta blobs over the writer's work; ISSUE 15)
    p_srv.add_argument("--quiet", action="store_true")
    p_srv.set_defaults(fn=cmd_serve)

    p_rt = sub.add_parser(
        "route",
        help="jax-free fleet query router (ISSUE 18): dispatch "
             "membership queries over `cli serve --fleet` replicas by "
             "node range, scatter-gather members_of across shards, pin "
             "every query to the fleet-wide serving generation "
             "(barrier-free rollout), pick the least-loaded healthy "
             "replica",
    )
    p_rt.add_argument(
        "--fleet", required=True, metavar="DIR",
        help="fleet publication directory (`cli fit --publish-shards`):"
             " the manifest's row ranges are the routing table",
    )
    p_rt.add_argument(
        "--endpoints", default=None, metavar="HOST:PORT,...",
        help="comma-separated replica endpoints (every replica of "
             "every shard; shard ownership is discovered from their "
             "status answers); alternative: --members",
    )
    p_rt.add_argument(
        "--members", default=None, metavar="FILE",
        help="watched membership file (published by `cli fleet up`): "
             "the endpoint set follows it — add-replica/drain reshape "
             "the fleet mid-stream with zero dropped queries "
             "(ISSUE 20)",
    )
    p_rt.add_argument(
        "--wait-fleet-s", type=float, default=30.0,
        help="with --members: how long to wait for the fleet to come "
             "up before erroring (kills the start-order race between "
             "`fleet up` and `route`)",
    )
    p_rt.add_argument(
        "--daemon", action="store_true",
        help="serve the router itself over the replica wire (newline-"
             "framed JSON TCP, --listen): a long-lived tier instead of "
             "a one-shot batch — `{\"family\": \"status\"}` answers "
             "router.stats(), `{\"family\": \"stop\"}` shuts it down",
    )
    p_rt.add_argument(
        "--listen", default=None, metavar="HOST:PORT",
        help="--daemon bind address (port 0 picks a free port; the "
             "chosen endpoint is printed as JSON before serving)",
    )
    p_rt.add_argument(
        "--deadline-s", type=float, default=0.0,
        help="per-query wall deadline: a routed query past it answers "
             "{\"error\": \"deadline_exceeded\"} (counted + rate-"
             "verdicted; 0 = off)",
    )
    p_rt.add_argument(
        "--retry-rounds", type=int, default=1,
        help="refresh+re-dispatch rounds an idempotent read sub-query "
             "gets after EVERY replica of its shard failed — the "
             "window in which the supervisor restarts a killed "
             "replica (0 = fail immediately)",
    )
    p_rt.add_argument(
        "--hedge", action="store_true",
        help="tail-latency hedging: duplicate a slow read sub-query "
             "to a second replica after --hedge-delay-s (first answer "
             "wins, loser cancelled; hedged/hedge_wins counted)",
    )
    p_rt.add_argument(
        "--hedge-delay-s", type=float, default=0.0,
        help="explicit hedge delay (0 = derive from the rolling wire "
             "p99 once --hedge-min-samples accumulated)",
    )
    p_rt.add_argument(
        "--hedge-min-samples", type=int, default=64,
        help="wire-latency samples needed before a derived hedge "
             "delay engages",
    )
    p_rt.add_argument(
        "--query", action="append", default=None, metavar="FAMILY:ARG",
        help="one query: communities_of:<u>, members_of:<c>, "
             "suggest_for:<u>, or a JSON object (repeatable)",
    )
    p_rt.add_argument(
        "--queries", default=None,
        help="JSONL file of query objects (one per line) — the load-"
             "test path (scripts/fleet_gate.py generates Zipf mixes)",
    )
    p_rt.add_argument(
        "--results", default=None,
        help="write one JSON answer per query line here (default: "
             "answers echo to stdout only for <= 16 queries)",
    )
    p_rt.add_argument(
        "--repeat", type=int, default=1,
        help="run the query set this many times (load testing)",
    )
    p_rt.add_argument(
        "--max-workers", type=int, default=16,
        help="concurrent in-flight queries (the open-loop driver's "
             "parallelism)",
    )
    p_rt.add_argument(
        "--health-interval-s", type=float, default=0.0,
        help="re-health-check replicas and re-evaluate the serving "
             "generation every this many seconds (0 = only at startup; "
             "the barrier-free rollout needs this to flip mid-stream)",
    )
    p_rt.add_argument(
        "--request-timeout-s", type=float, default=60.0,
        help="per-sub-query TCP timeout before failing over to the "
             "next replica of the shard",
    )
    p_rt.add_argument(
        "--stop", action="store_true",
        help="send a stop op to every endpoint and exit (fleet "
             "teardown; no queries run)",
    )
    p_rt.add_argument(
        "--telemetry-dir", default=None,
        help="run-telemetry directory: route events + the final router "
             "stats (render with `cli report`; jax-free on this entry)",
    )
    p_rt.add_argument(
        "--heartbeat-s", type=float, default=300.0,
        help="stall-heartbeat deadline with --telemetry-dir "
             "(0 disables)",
    )
    p_rt.add_argument(
        "--perf-ledger", default=None,
        help="append this route run's record (router p50/p99/QPS/shed "
             "rate, shards x replicas in the match key) to a "
             "perf-ledger JSONL; `cli perf diff` VERDICTS them",
    )
    p_rt.add_argument("--quiet", action="store_true")
    p_rt.set_defaults(fn=cmd_route)

    p_fl = sub.add_parser(
        "fleet",
        help="jax-free fleet supervisor (ISSUE 20): own the `serve "
             "--fleet` replica processes — restart-on-exit with "
             "RetryPolicy backoff, crash-loop quarantine, membership "
             "published to a watched file `cli route --members` "
             "follows; up/status/down/add-replica/drain",
    )
    p_fl.add_argument(
        "action",
        choices=["up", "status", "down", "add-replica", "drain"],
        help="up: spawn + supervise (parks until a down op); the rest "
             "talk to a running supervisor's --control endpoint",
    )
    p_fl.add_argument(
        "--fleet", default=None, metavar="DIR",
        help="fleet publication directory (`cli fit --publish-shards`)",
    )
    p_fl.add_argument(
        "--shards", type=int, default=1,
        help="up: shards in the fleet manifest",
    )
    p_fl.add_argument(
        "--replicas", type=int, default=1,
        help="up: replicas per shard",
    )
    p_fl.add_argument("--host", default="127.0.0.1",
                      help="bind host for replicas + control")
    p_fl.add_argument(
        "--control-port", type=int, default=0,
        help="up: control socket port (0 picks; printed in the hello)",
    )
    p_fl.add_argument(
        "--control", default=None, metavar="HOST:PORT",
        help="status/down/add-replica/drain: the control endpoint "
             "`fleet up` printed",
    )
    p_fl.add_argument(
        "--members", default=None, metavar="FILE",
        help="membership file path (default: <fleet>/members.json); "
             "status can read it directly without --control",
    )
    p_fl.add_argument(
        "--graph", default=None,
        help="compiled graph cache passed to every replica "
             "(suggest_for needs it)",
    )
    p_fl.add_argument(
        "--watch-snapshots", type=float, default=1.0,
        help="replica snapshot poll interval: how a RESTARTED replica "
             "rejoins at the newest generation (0 = off)",
    )
    p_fl.add_argument(
        "--replica-args", default=None, metavar="'ARGS...'",
        help="extra `cli serve` flags passed through to every replica "
             "(shell-quoted string, e.g. '--max-queue-depth 256')",
    )
    p_fl.add_argument(
        "--log-dir", default=None,
        help="per-member replica stderr logs (default: discarded)",
    )
    p_fl.add_argument(
        "--restart-base-s", type=float, default=0.25,
        help="restart backoff base (RetryPolicy schedule: base * "
             "factor^n with deterministic per-member jitter)",
    )
    p_fl.add_argument(
        "--restart-max-s", type=float, default=10.0,
        help="restart backoff ceiling",
    )
    p_fl.add_argument(
        "--quarantine-after", type=int, default=3,
        help="consecutive failures (never up for --stable-s) before a "
             "slot is quarantined — crash-loop detection",
    )
    p_fl.add_argument(
        "--stable-s", type=float, default=5.0,
        help="uptime that resets a member's failure count",
    )
    p_fl.add_argument(
        "--drain-grace-s", type=float, default=0.5,
        help="drain: wait this long after publishing state=draining "
             "before the wire drain op (one router reload interval — "
             "the zero-drop handshake)",
    )
    p_fl.add_argument(
        "--up-timeout-s", type=float, default=60.0,
        help="up: how long to wait for every replica's hello before "
             "printing all_up=false (supervision continues either way)",
    )
    p_fl.add_argument(
        "--member", default=None, metavar="ID",
        help="drain: which member (e.g. s0r1)",
    )
    p_fl.add_argument(
        "--shard", type=int, default=0,
        help="add-replica: which shard the new replica serves",
    )
    p_fl.add_argument("--seed", type=int, default=0,
                      help="backoff-jitter seed")
    p_fl.add_argument(
        "--telemetry-dir", default=None,
        help="run-telemetry directory: membership / replica_restart / "
             "replica_quarantined events + the final supervision "
             "counters (render with `cli report`; jax-free)",
    )
    p_fl.add_argument(
        "--heartbeat-s", type=float, default=0.0,
        help="stall-heartbeat deadline with --telemetry-dir "
             "(0 disables)",
    )
    p_fl.add_argument(
        "--perf-ledger", default=None,
        help="append the supervision record (replica_restarts) to a "
             "perf-ledger JSONL",
    )
    p_fl.add_argument("--quiet", action="store_true")
    p_fl.set_defaults(fn=cmd_fleet)

    p_ref = sub.add_parser(
        "refit",
        help="warm-start incremental refit (ISSUE 15): start from the "
             "latest published snapshot, re-optimize only the rows a "
             "delta touched (+ halo) via batched fold-in, publish the "
             "next generation; health detectors escalate accumulated "
             "drift to a full fit",
    )
    _add_common(p_ref)
    p_ref.add_argument(
        "--snapshots", required=True,
        help="snapshot directory (`cli fit --publish-dir`): the latest "
             "published F is the warm start, and the refit publishes "
             "the next generation here",
    )
    p_ref.add_argument(
        "--delta", required=True, metavar="EDGE_FILE",
        help="the delta edge file that was applied to the cache (`cli "
             "ingest --delta`): its endpoints are the touched rows",
    )
    p_ref.add_argument(
        "--k", type=int, default=None,
        help="community count (default: the snapshot's k; a mismatch "
             "refuses — a refit continues the same model)",
    )
    p_ref.add_argument(
        "--halo", type=int, default=1,
        help="refit touched rows plus this many hops of neighbors",
    )
    p_ref.add_argument(
        "--refit-rounds", type=int, default=12,
        help="block-coordinate sweep cap (detectors may stop earlier)",
    )
    p_ref.add_argument(
        "--refit-batch", type=int, default=512,
        help="fold-in rows per device batch (padded to a power of two "
             "for compile-cache reuse)",
    )
    p_ref.add_argument(
        "--foldin-max-iters", type=int, default=100,
        help="per-node fold-in iteration cap inside each batch",
    )
    p_ref.add_argument(
        "--escalate", default="full", choices=["full", "never"],
        help="on a divergence/plateau detector firing against the "
             "restricted objective: run a full fit warm-started from "
             "the refit F (full, default), or publish the refit F "
             "anyway with the escalated flag recorded (never)",
    )
    p_ref.add_argument(
        "--no-publish", action="store_true",
        help="skip publishing the result (measurement/CI runs)",
    )
    p_ref.set_defaults(fn=cmd_refit)

    p_pre = sub.add_parser(
        "preflight",
        help="jax-free capacity verdict: predicted per-device HBM, "
             "per-host RSS, and bytes/step for a config + graph + "
             "device target, with the binding constraint and the knobs "
             "that relax it (exit 0 fits / 2 does not fit)",
    )
    p_pre.add_argument(
        "--graph", required=True,
        help="compiled graph-cache dir (exact manifest numbers) or a "
             "SNAP text path (+ --nodes; edges estimated from size)",
    )
    p_pre.add_argument("--k", type=int, required=True)
    p_pre.add_argument(
        "--nodes", type=int, default=None,
        help="node count (required for text graphs; overrides a cache)",
    )
    p_pre.add_argument(
        "--edges", type=int, default=None,
        help="undirected edge count (overrides the estimate/manifest)",
    )
    p_pre.add_argument("--dtype", default="float32",
                       choices=["float32", "float64"])
    p_pre.add_argument(
        "--mesh", default=None, help="'DP,TP' target mesh (default: "
        "--devices,1)",
    )
    p_pre.add_argument(
        "--devices", type=int, default=1,
        help="target device count when --mesh is not given",
    )
    from bigclam_tpu.obs.memory import DEVICE_HBM_BYTES as _HBM

    p_pre.add_argument(
        "--device-kind", default=None, choices=sorted(_HBM),
        help="per-chip HBM from the builtin table "
             "(--hbm-gb overrides)",
    )
    p_pre.add_argument("--hbm-gb", type=float, default=None,
                       help="per-device HBM budget in GiB")
    p_pre.add_argument(
        "--hbm-bytes", type=float, default=None,
        help="exact per-device HBM budget in bytes (testing/gates)",
    )
    p_pre.add_argument("--host-ram-gb", type=float, default=None,
                       help="per-host RAM budget in GiB")
    p_pre.add_argument("--processes", type=int, default=1,
                       help="host process count (per-host RSS divisor "
                       "for the store-native stages)")
    p_pre.add_argument("--representation", default="dense",
                       choices=["dense", "sparse"])
    p_pre.add_argument("--sparse-m", type=int, default=64)
    p_pre.add_argument("--support-every", type=int, default=1)
    p_pre.add_argument("--schedule", default="allgather",
                       choices=["allgather", "ring"])
    p_pre.add_argument("--store-native", action="store_true")
    p_pre.add_argument(
        "--partition", default="1d", choices=["1d", "2d"],
        help="price the 1d all-gather layout or the 2d closure-gather "
             "layout (a 1d does-not-fit verdict names --partition 2d "
             "when it would relax the binding gather)",
    )
    p_pre.add_argument(
        "--replica-cols", type=int, default=1,
        help="columns of the --partition 2d grid (rows = p / cols)",
    )
    p_pre.add_argument("--health-every", type=int, default=10)
    p_pre.add_argument("--max-backtracks", type=int, default=15)
    p_pre.add_argument("--edge-chunk", type=int, default=None)
    p_pre.add_argument(
        "--chunk-bytes", type=int, default=0,
        help="include the ingest stage in the host model at this "
             "chunk budget (0 = fit-only stages)",
    )
    p_pre.add_argument("--csr-block-b", type=int, default=256)
    p_pre.add_argument(
        "--serve", action="store_true",
        help="price a SERVING fleet instead of a fit (ISSUE 18): "
             "per-replica RSS (sparse-aware snapshot + inverted index "
             "+ cache + adjacency slice) and fleet QPS capacity vs "
             "--qps-target, jax-free; same exit-code contract",
    )
    p_pre.add_argument(
        "--serve-shards", type=int, default=1,
        help="--serve: row-range shards the fleet is split into",
    )
    p_pre.add_argument(
        "--serve-replicas", type=int, default=1,
        help="--serve: replicas per shard",
    )
    p_pre.add_argument(
        "--qps-target", type=float, default=0.0,
        help="--serve: offered load to verdict fleet capacity against "
             "(0 = report capacity without a verdict)",
    )
    p_pre.add_argument(
        "--qps-per-replica", type=float, default=9000.0,
        help="--serve: read-family throughput of one replica (measure "
             "with scripts/serve_gate.py on target hardware)",
    )
    p_pre.add_argument(
        "--serve-cache-slots", type=int, default=64,
        help="--serve: hot-community cache capacity per replica",
    )
    p_pre.add_argument(
        "--avg-memberships", type=float, default=2.0,
        help="--serve: expected communities per node (sizes the "
             "inverted index and the cached member lists)",
    )
    p_pre.add_argument("--json", action="store_true",
                       help="machine-readable verdict")
    p_pre.set_defaults(fn=cmd_preflight)

    p_eval = sub.add_parser("eval", help="score predicted vs ground-truth communities")
    p_eval.add_argument("--pred", required=True)
    p_eval.add_argument("--truth", required=True)
    p_eval.add_argument("--no-nmi", action="store_true")
    p_eval.set_defaults(fn=cmd_eval)

    args = ap.parse_args(argv)
    # platform/precision must be pinned before the first jax backend use
    # (env vars are too late when the host env pre-imports jaxlib)
    if getattr(args, "platform", None):
        import jax

        jax.config.update("jax_platforms", args.platform)
        if args.platform == "cpu" and getattr(args, "mesh", None):
            from bigclam_tpu.utils.dist import request_cpu_devices

            dp, tp = (int(x) for x in args.mesh.split(","))
            request_cpu_devices(dp * tp)
    if getattr(args, "dtype", None) == "float64":
        import jax

        jax.config.update("jax_enable_x64", True)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
