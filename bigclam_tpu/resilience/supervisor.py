"""Retry/backoff supervisor + resume lineage (ISSUE 5 tentpole, parts 2/4).

The Supervisor is the orchestration shim between the CLI entry points and
the work they run: graph loads, checkpoint I/O, and whole fit attempts go
through `call`/`run_fit`, which retries transient-classified failures with
backoff (retry.py) and converts the heartbeat's stall escalation into a
retryable abort. A fit retried this way re-enters `model.fit(...,
checkpoints=...)` and therefore RESUMES from the newest valid checkpoint —
retry is recovery, not repetition.

Stall escalation: the watchdog thread (obs.heartbeat) cannot cancel a
wedged collective, but a HOST-side stall (a hung filesystem read, a
deadlocked spawn pool) is interruptible. With `abort_on_stall=True` the
supervisor's escalation hook raises KeyboardInterrupt in the main thread
(`_thread.interrupt_main`), `run_fit` converts it to a transient
StallEscalation, and the attempt retries/resumes. Default off: for device
stalls interruption cannot help, and the escalated event alone is the
right behavior.

Resume lineage: every `--resume auto` that actually restores appends an
attempt record to `resume_lineage.json` in the telemetry directory — the
run id (shared across attempts through the run-id claim file), a fresh
attempt id, the resumed step, and the wall time — and emits a `resume`
event. `cli report` renders the chain.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from bigclam_tpu.resilience.retry import (
    RetryPolicy,
    call_with_retry,
    classify,
)

LINEAGE_NAME = "resume_lineage.json"


class StallEscalation(RuntimeError):
    """The stall watchdog escalated and aborted this attempt (transient:
    the retried attempt resumes from the newest checkpoint)."""


def classify_with_escalation(exc: BaseException) -> str:
    if isinstance(exc, StallEscalation):
        return "transient"
    return classify(exc)


class Supervisor:
    """Wraps fallible stages with classified retry and owns the heartbeat
    escalation hook. One per entry-point invocation."""

    def __init__(
        self,
        policy: Optional[RetryPolicy] = None,
        abort_on_stall: bool = False,
    ):
        self.policy = policy or RetryPolicy()
        self.abort_on_stall = abort_on_stall
        self.escalations = 0
        self._escalated = threading.Event()

    # ------------------------------------------------------------ wiring
    def attach(self, telemetry) -> "Supervisor":
        """Register as the stall-escalation sink of `telemetry`'s
        heartbeat (no-op when telemetry/heartbeat is off)."""
        hb = getattr(telemetry, "heartbeat", None)
        if hb is not None:
            hb.on_escalate = self._on_escalate
        return self

    def _on_escalate(self, info: dict) -> None:
        # called from the watchdog thread
        self.escalations += 1
        self._escalated.set()
        if self.abort_on_stall:
            import _thread

            _thread.interrupt_main()

    # ------------------------------------------------------------- calls
    def call(self, site: str, fn: Callable):
        """Retry `fn` under the policy (transient errors only)."""
        return call_with_retry(
            fn, site, self.policy, classify_fn=classify_with_escalation
        )

    def run_fit(self, fit_fn: Callable, site: str = "fit"):
        """Run a whole fit attempt under retry. The attempt closure should
        re-enter model.fit with its CheckpointManager so a retried attempt
        resumes instead of restarting."""

        def attempt():
            self._escalated.clear()
            try:
                return fit_fn()
            except KeyboardInterrupt:
                if self._escalated.is_set():
                    raise StallEscalation(
                        "stall watchdog escalated; aborting this attempt "
                        "for a resumed retry"
                    ) from None
                raise

        return self.call(site, attempt)


# --------------------------------------------------------------------------
# resume lineage
# --------------------------------------------------------------------------


def read_lineage(directory: str) -> List[Dict[str, Any]]:
    path = os.path.join(directory, LINEAGE_NAME)
    try:
        with open(path) as f:
            out = json.load(f)
        return out if isinstance(out, list) else []
    except (OSError, ValueError):
        return []


def record_resume(
    directory: Optional[str],
    resumed_step: int,
    run_id: Optional[str] = None,
    extra: Optional[dict] = None,
) -> Optional[dict]:
    """Append one attempt record to the lineage file (primary process
    only — pid via the telemetry-safe probe, never a cold jax init) and
    emit a `resume` event. `directory` None (no telemetry dir) still emits
    the event when telemetry is active elsewhere; returns the record."""
    from bigclam_tpu.obs import telemetry as _obs

    tel = _obs.current()
    if run_id is None and tel is not None:
        run_id = tel.run_id
    entry = {
        "attempt_id": os.urandom(3).hex(),
        "run": run_id,
        "resumed_step": int(resumed_step),
        "unix": round(time.time(), 3),
        **(extra or {}),
    }
    if tel is not None:
        tel.event(
            "resume",
            step=int(resumed_step),
            attempt_id=entry["attempt_id"],
            prev_attempts=(
                len(read_lineage(directory)) if directory else 0
            ),
        )
    if directory and _obs._process_index() == 0:
        lineage = read_lineage(directory)
        lineage.append(entry)
        path = os.path.join(directory, LINEAGE_NAME)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(lineage, f, indent=1)
        os.replace(tmp, path)
    return entry
