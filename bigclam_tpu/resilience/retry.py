"""Classified retry with exponential backoff (ISSUE 5 tentpole, part 2).

Error taxonomy (DESIGN.md "Failure model & recovery"): an I/O hiccup on a
shared filesystem, a slow-to-appear coordinator, or an injected transient
is worth retrying with backoff; a config/shape mismatch, a checksum that
fails identically every read, or an exhausted rollback budget is NOT — the
retry would deterministically reproduce it. `classify` encodes that split,
call sites can extend it (the supervisor classifies its stall-escalation
interrupt as transient), and every attempt/outcome is emitted under the
PR 4 telemetry schema (`retry` / `recovered` / `gave_up`) so `cli report`
can render a run's recovery history.

Backoff is exponential with DETERMINISTIC jitter: the jitter stream is
seeded from (policy.seed, site), so two runs of the same plan back off
identically — chaos tests stay reproducible — while distinct sites (and
distinct-seed runs on a pod) still decorrelate.
"""

from __future__ import annotations

import dataclasses
import time
import zipfile
import zlib
from typing import Callable, Optional

import numpy as np


class TransientError(RuntimeError):
    """Explicitly transient-classified wrapper for call sites."""


class FatalError(RuntimeError):
    """Explicitly fatal-classified wrapper (never retried)."""


# exception types worth a retry: environmental, usually self-healing
_TRANSIENT_TYPES = (
    OSError, EOFError, ConnectionError, TimeoutError, InterruptedError,
    zlib.error, zipfile.BadZipFile,
)


def classify(exc: BaseException) -> str:
    """"transient" or "fatal" (see module docstring). FileNotFoundError is
    deliberately transient: on shared filesystems a just-renamed checkpoint
    or shard can lag visibility across hosts by seconds."""
    if isinstance(exc, FatalError):
        return "fatal"
    if isinstance(exc, TransientError):
        return "transient"
    if isinstance(exc, _TRANSIENT_TYPES):
        return "transient"
    return "fatal"


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Per-class attempt budgets + backoff shape. `transient_attempts` is
    the TOTAL attempt count (1 = no retry); fatal errors get exactly
    `fatal_attempts` (default 1: fail fast, the retry would reproduce)."""

    transient_attempts: int = 3
    fatal_attempts: int = 1
    base_s: float = 0.05
    factor: float = 2.0
    max_s: float = 5.0
    jitter: float = 0.5
    seed: int = 0

    def attempts_for(self, cls: str) -> int:
        return max(
            self.transient_attempts if cls == "transient"
            else self.fatal_attempts,
            1,
        )

    def backoff_s(self, failure_index: int, rng) -> float:
        base = min(self.base_s * self.factor ** failure_index, self.max_s)
        return base * (1.0 + self.jitter * float(rng.random()))


def call_with_retry(
    fn: Callable,
    site: str,
    policy: Optional[RetryPolicy] = None,
    classify_fn: Callable[[BaseException], str] = classify,
    sleep: Callable[[float], None] = time.sleep,
):
    """Run `fn()` under the policy, emitting retry/recovered/gave_up
    telemetry events tagged with `site`. Raises the final error after the
    class budget is exhausted (or immediately for fatal classes)."""
    from bigclam_tpu.obs import telemetry as _obs

    policy = policy or RetryPolicy()
    rng = np.random.default_rng([policy.seed, zlib.crc32(site.encode())])
    failures = 0
    while True:
        tel = _obs.current()
        try:
            out = fn()
        except Exception as e:
            cls = classify_fn(e)
            failures += 1
            err = f"{type(e).__name__}: {e}"[:300]
            if failures >= policy.attempts_for(cls):
                if tel is not None:
                    tel.event(
                        "gave_up", site=site, attempts=failures,
                        error=err, error_class=cls,
                    )
                raise
            back = policy.backoff_s(failures - 1, rng)
            if tel is not None:
                tel.event(
                    "retry", site=site, attempt=failures,
                    backoff_s=round(back, 4), error=err, error_class=cls,
                )
            sleep(back)
            continue
        if failures and tel is not None:
            tel.event("recovered", site=site, attempts=failures + 1)
        return out
