"""Fault-tolerant fit orchestration (ISSUE 5): detection -> recovery.

PR 4 made failures VISIBLE (stall/nonfinite telemetry events, checksum
rejection); this package makes them SURVIVABLE, layer by layer:

* faults.py      — deterministic seeded fault injection (kill / delay /
                   NaN / truncate / corrupt) at instrumented sites, driven
                   by tests, the chaos gate, and BIGCLAM_FAULTS
* retry.py       — classified (transient vs fatal) retry with seeded
                   exponential backoff, emitting retry/recovered/gave_up
                   telemetry events
* supervisor.py  — the orchestration shim: whole-fit retry that resumes
                   from checkpoints, stall-escalation abort hook, and the
                   resume lineage record behind `cli fit --resume auto`

The in-loop recovery mechanisms live where the loops live: non-finite
ROLLBACK in models.bigclam.run_fit_loop (snapshot ping-pong + step-scale
cut), checkpoint payload crc + corruption-safe rotation in
utils.checkpoint, and shard QUARANTINE + re-ingest in graph.store.
"""

from bigclam_tpu.resilience.faults import (
    FaultPlan,
    current_plan,
    install_plan,
    maybe_fire,
)
from bigclam_tpu.resilience.retry import (
    FatalError,
    RetryPolicy,
    TransientError,
    call_with_retry,
    classify,
)
from bigclam_tpu.resilience.supervisor import (
    StallEscalation,
    Supervisor,
    read_lineage,
    record_resume,
)

__all__ = [
    "FatalError",
    "FaultPlan",
    "RetryPolicy",
    "StallEscalation",
    "Supervisor",
    "TransientError",
    "call_with_retry",
    "classify",
    "current_plan",
    "install_plan",
    "maybe_fire",
    "read_lineage",
    "record_resume",
]
