"""Deterministic fault-injection harness (ISSUE 5 tentpole, part 1).

The recovery paths this package adds (retry, rollback, quarantine,
auto-resume) are only trustworthy if tests and the chaos gate can drive the
REAL failure paths on demand — a preemption that only ever happens on a
pod is a recovery path that has never run. This module injects seeded,
deterministic faults at instrumented sites:

    fit.step           every run_fit_loop iteration (ctx: it)
    checkpoint.save    after each CheckpointManager.save (ctx: step, path)
    store.load_shard   before each shard blob read (ctx: shard, path)
    replica.start      a fleet replica process about to serve (ctx:
                       member, shard) — a kill here is a crash loop, the
                       supervisor's quarantine drill (ISSUE 20)
    replica.answer_write
                       a replica about to write one answer frame (ctx:
                       member, shard, family) — the wire-fault kinds below
                       fire here (serve.fleet applies them)
    wire.connect       the router about to dial a replica endpoint (ctx:
                       endpoint) — connect_refuse fires here

Fault kinds:

    kill               SIGKILL this process (true preemption: no handlers,
                       no atexit — exactly what a borg eviction looks like)
    delay              sleep `seconds` on the host (straggler / slow DCN
                       hop approximation) before the site proceeds
    nan_inject         the fit loop poisons F[index] with NaN (drives the
                       non-finite rollback path end to end)
    truncate_checkpoint / corrupt_checkpoint
                       applied by checkpoint.save to the just-renamed file
                       (a lost page-cache writeback / silent bit flip)
    corrupt_shard      applied by store.load_shard to the shard's indices
                       blob before the crc check (drives quarantine)
    connect_refuse     wire.connect raises ConnectionRefusedError (the
                       endpoint's process is gone; the router must fail
                       over, not error)
    torn_frame         replica.answer_write emits HALF the answer frame
                       then hangs up (a peer killed mid-write) — the
                       router's bounded reader must discard + retry
    garbage_line       replica.answer_write emits a non-JSON line (framing
                       corruption) — same recovery contract
    stall              replica.answer_write sleeps `seconds` BEFORE
                       writing (a wedged replica) — the router's read
                       timeout must bound it, then fail over

A plan is a JSON spec: ``{"seed": 0, "faults": [{"kind": "kill", "site":
"fit.step", "at": 5}, ...]}``. Each fault fires ONCE (consumed); matching
is deterministic: ``at`` matches the site's iteration (fit.step) or its
0-based hit count (other sites); any other spec key that a site passes as
context must match exactly (e.g. ``shard``/``step``); an optional ``pid``
restricts the fault to one process of a multi-controller run.

Activation: ``install_plan(FaultPlan.from_spec(...))`` in-process, or the
``BIGCLAM_FAULTS`` env var (inline JSON, or ``@/path/to/plan.json``) so
subprocess tests and the chaos gate drive CLI entry points. With no plan
installed every site costs one module-dict lookup.

jax-free at import (checkpoint.py and store.py are jax-free and must stay
so); the one jax-touching fault (nan_inject) is APPLIED by the fit loop,
not here — this module only matches specs and mutates files/processes.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

import numpy as np

ENV_VAR = "BIGCLAM_FAULTS"

# spec keys with harness-level meaning; everything else is a context match
_RESERVED = {"kind", "site", "at", "pid", "seconds", "frac", "offset",
             "index"}

_STATE: Dict[str, Any] = {"plan": None, "env_checked": False}


class FaultPlan:
    """A consumable, seeded list of fault specs (see module docstring)."""

    def __init__(self, faults: List[dict], seed: int = 0):
        self.faults = [dict(f) for f in faults]
        self.seed = int(seed)
        self.rng = np.random.default_rng(self.seed)
        self.fired: List[dict] = []
        self._consumed = [False] * len(self.faults)
        self._hits: Dict[str, int] = {}
        for f in self.faults:
            if "kind" not in f or "site" not in f:
                raise ValueError(f"fault spec needs kind+site: {f!r}")

    @classmethod
    def from_spec(cls, spec: dict) -> "FaultPlan":
        return cls(spec.get("faults", []), seed=spec.get("seed", 0))

    @classmethod
    def from_env(cls, value: Optional[str] = None) -> Optional["FaultPlan"]:
        raw = os.environ.get(ENV_VAR) if value is None else value
        if not raw:
            return None
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                raw = f.read()
        return cls.from_spec(json.loads(raw))

    def _matches(self, spec: dict, site: str, n: int, ctx: dict) -> bool:
        if spec["site"] != site:
            return False
        if spec.get("pid") is not None:
            if _process_index() != int(spec["pid"]):
                return False
        if "at" in spec:
            anchor = ctx["it"] if "it" in ctx else n
            if int(anchor) != int(spec["at"]):
                return False
        for key, val in spec.items():
            if key in _RESERVED or key not in ctx:
                continue
            if ctx[key] != val:
                return False
        return True

    def fire(self, site: str, **ctx) -> Optional[dict]:
        """The first unconsumed spec matching this site hit, or None.
        Consumes the spec, emits a `fault_injected` telemetry event, and
        applies the site-independent kinds (kill/delay) in place."""
        n = self._hits.get(site, 0)
        self._hits[site] = n + 1
        for i, spec in enumerate(self.faults):
            if self._consumed[i] or not self._matches(spec, site, n, ctx):
                continue
            self._consumed[i] = True
            self.fired.append(spec)
            _event(site, spec, ctx)
            kind = spec["kind"]
            if kind == "kill":
                print(
                    f"[bigclam] FAULT kill at {site} "
                    f"(ctx={_small(ctx)}): SIGKILL",
                    file=sys.stderr,
                    flush=True,
                )
                import signal

                os.kill(os.getpid(), signal.SIGKILL)
            if kind == "delay":
                time.sleep(float(spec.get("seconds", 0.05)))
            return dict(spec)
        return None

    def apply_to_file(self, spec: dict, path: str) -> None:
        """Mutate `path` per a truncate_*/corrupt_* spec (deterministic:
        offsets default to mid-file; fractions to 0.5)."""
        size = os.path.getsize(path)
        kind = spec["kind"]
        if kind.startswith("truncate"):
            keep = int(size * float(spec.get("frac", 0.5)))
            with open(path, "r+b") as f:
                f.truncate(keep)
            return
        if kind.startswith("corrupt"):
            offset = int(spec.get("offset", max(size // 2, 0)))
            offset = min(max(offset, 0), max(size - 1, 0))
            with open(path, "r+b") as f:
                f.seek(offset)
                b = f.read(1) or b"\x00"
                f.seek(offset)
                f.write(bytes([b[0] ^ 0xFF]))
            return
        raise ValueError(f"fault kind {kind!r} is not a file fault")


def _small(ctx: dict) -> dict:
    return {k: v for k, v in ctx.items() if isinstance(v, (int, str, float))}


def _process_index() -> int:
    from bigclam_tpu.obs.telemetry import _process_index as pidx

    return pidx()


def _event(site: str, spec: dict, ctx: dict) -> None:
    from bigclam_tpu.obs import telemetry as _obs

    tel = _obs.current()
    if tel is not None:
        tel.event(
            "fault_injected", site=site, fault=spec["kind"],
            spec={k: v for k, v in spec.items()}, **_small(ctx),
        )


def install_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install (or with None, clear) the process-wide plan. Clearing also
    resets the env latch so a later install/env change is honored."""
    _STATE["plan"] = plan
    _STATE["env_checked"] = plan is not None
    return plan


def current_plan() -> Optional[FaultPlan]:
    plan = _STATE["plan"]
    if plan is None and not _STATE["env_checked"]:
        _STATE["env_checked"] = True
        plan = FaultPlan.from_env()
        _STATE["plan"] = plan
    return plan


def maybe_fire(site: str, **ctx) -> Optional[dict]:
    """The instrumented-site entry point: near-free when no plan is active
    (one dict lookup), else FaultPlan.fire."""
    plan = _STATE["plan"]
    if plan is None:
        if _STATE["env_checked"]:
            return None
        plan = current_plan()
        if plan is None:
            return None
    return plan.fire(site, **ctx)


def apply_wire_fault(spec: dict, wfile, payload: bytes) -> Optional[str]:
    """Apply a replica.answer_write wire fault to one outgoing answer
    frame. Returns what the transport handler must do next:

      "close"  — torn_frame: half the frame went out, hang up now
      "skip"   — garbage_line: a non-JSON line replaced the answer;
                 keep the connection (the peer discards it)
      None     — stall (the sleep already happened) or an unknown kind:
                 write the real answer normally
    """
    kind = spec["kind"]
    if kind == "torn_frame":
        wfile.write(payload[: max(len(payload) // 2, 1)])
        wfile.flush()
        return "close"
    if kind == "garbage_line":
        wfile.write(b"!! injected garbage frame !!\n")
        wfile.flush()
        return "skip"
    if kind == "stall":
        time.sleep(float(spec.get("seconds", 1.0)))
        return None
    return None


def apply_file_fault(spec: dict, path: str) -> None:
    """Module-level convenience for sites: apply a file fault using the
    installed plan's determinism (falls back to a throwaway plan when the
    spec arrived without one — offsets are explicit or mid-file anyway)."""
    plan = _STATE["plan"] or FaultPlan([])
    plan.apply_to_file(spec, path)
