"""Headline benchmark: BigCLAM optimizer throughput on the available
accelerator — Email-Enron K=100 (BASELINE config 2) plus a representative
grouped-path config (synthetic AGM, N=300K, K=1000 — the large-K regime
PARITY.md's 8.4x claim lives in), each timed on BOTH the blocked-CSR kernel
path and the XLA fallback so "kernels are faster" is continuously verified.

Prints ONE JSON line:
  {"metric": "edges/sec/chip", "value": N, "unit": "edges/sec/chip",
   "vs_baseline": R, "path": "csr", "configs": {...}, ...}

metric/value: directed-edge traversals per second per chip on Email-Enron
K=100 over the CSR path (the round-over-round comparable headline; one
optimizer iteration = ONE traversal of the 2E directed edges; multiply by
17 for raw gather-dot sweeps). value = median over timing windows; every
window is recorded with its [start, end] timestamps (seconds since bench
start) so burst-then-settle patterns (clock boost vs compilation residue)
are visible in the artifact instead of folklore.

vs_baseline: speedup over the float64 NumPy spec interpreter (exact
reference semantics, SURVEY.md §4.2) running the same iteration on this
host's CPU — the reference publishes no numbers (BASELINE.md), so the
oracle's single-core throughput is the anchor. The baseline is the MEDIAN
of >= 3 interpreter iterations (a single shared-CPU iteration wobbled the
round-2/3 scoreboards by 11%).

On a TPU backend the CSR kernels MUST engage for the headline configs — a
silent XLA fallback fails the run rather than polluting the scoreboard.

A hung/crashed accelerator init (BENCH_r05: the axon relay) re-execs the
benchmark on the CPU platform with a reduced config set; the record is
tagged "backend": "cpu-fallback" so the scoreboard can tell a degraded
measurement from a healthy one. With >= 2 devices a ring-schedule config
additionally reports edges/sec/chip under the overlapped vs the serialized
rotation schedule plus the comm-hidden fraction
(utils.profiling.overlap_report).
"""

import json
import os
import statistics
import time

import numpy as np

# headline graph: Email-Enron text by default; point BIGCLAM_BENCH_GRAPH at
# a graph-cache dir (cli ingest) to time the cached-reload data path — the
# record tags which one fed the run ("graph_source": "text" | "cache")
ENRON = os.environ.get(
    "BIGCLAM_BENCH_GRAPH", "/root/reference/data/Email-Enron.txt"
)
K_ENRON = 100
LARGE_N, LARGE_K, LARGE_P_IN = 300_000, 1000, 0.1
# K-blocked single-chip regime: K large enough that whole-K rows are
# refused by fit_tile_shape (~2500 at the default tile shape) and the
# csr_grouped_kb path must engage
XLK_N, XLK_K, XLK_P_IN = 60_000, 3000, 0.5
# ring overlap config: per-chip shard size / K for the overlapped-vs-serial
# rotation timing (scaled to the device count at runtime)
RING_PER_SHARD, RING_K, RING_STEPS = 2048, 8, 5
WINDOWS = 5
ITERS_PER_WINDOW = 10
WARMUP_ITERS = 3
LARGE_WINDOWS = 3
LARGE_ITERS_PER_WINDOW = 3
BASELINE_ITERS = 3

# set on the re-exec'd process when the accelerator backend init hung or
# crashed and the benchmark restarted itself on the CPU platform
FALLBACK_ENV = "BIGCLAM_BENCH_CPU_FALLBACK"

# observability env the re-exec MUST carry over: dropping any of these
# would silently strip the fallback run's telemetry/perf-ledger/fault
# plan (ISSUE 6 satellite — pinned by tests/test_trace.py)
PROPAGATED_ENV = (
    "BIGCLAM_TELEMETRY_DIR",
    "BIGCLAM_PERF_LEDGER",
    "BIGCLAM_FAULTS",
)


def _fallback_child_env(environ) -> dict:
    """The exact environment the cpu-fallback re-exec runs under: a COPY
    of the parent's (so BIGCLAM_TELEMETRY_DIR / BIGCLAM_PERF_LEDGER /
    BIGCLAM_FAULTS and everything else propagate), with the CPU platform
    pinned, the fallback tag set, and 8 virtual host devices so the ring
    overlap config still runs. Factored out so the propagation contract
    is testable without hanging a backend."""
    env = dict(environ)
    env["JAX_PLATFORMS"] = "cpu"
    env[FALLBACK_ENV] = "1"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    return env

# --- roofline / MFU accounting (VERDICT r5 Next #5) -----------------------
# edges/sec/chip is a RELATIVE number until it has a denominator: the
# fields below state how far each config sits from the chip's own limits,
# so "is it actually fast" is answerable from the artifact alone. The
# sweeps are gather-bound SpMM-shaped work — the expected roofline position
# is a high HBM fraction with ~1% MFU, and a LOW hbm_frac is the smell
# (it means gather/scatter latency, not bandwidth, is the wall).
SWEEPS_PER_ITER = 17          # 1 fused grad/LLH + 16 Armijo candidates

# device_kind substring -> (HBM GB/s, bf16 MXU peak TFLOP/s). Published
# per-chip numbers; MFU is quoted against the bf16 MXU peak (the kernels
# run f32 HIGHEST-precision matmuls, so the quoted MFU understates the
# f32-relative utilization by ~2x — stated here once rather than baked
# into the numbers).
DEVICE_PEAKS = {
    "v5 lite": (819.0, 197.0),       # v5e / v5litepod
    "v5p": (2765.0, 459.0),
    "v4": (1228.0, 275.0),
    "v3": (900.0, 123.0),
    "v2": (700.0, 45.0),
}


def roofline_model(k: int) -> dict:
    """Analytic per-directed-edge cost model of one optimizer iteration.

    bytes: every sweep streams BOTH endpoint F rows per edge visit
    (2*K*4 f32 — the ~800 B/edge-visit at K=100 from the round-5
    adjudication); the grad sweep additionally scatters one (K,) row of
    contributions (1 of the 17 sweeps). flops: the K-length dot (2K) per
    visit, plus the candidate row construction clip(F + eta*grad) (2K) on
    the 16 candidate sweeps. Index/mask traffic (~12 B/edge) is noise
    next to the rows and is left out of the model deliberately.
    """
    bytes_iter = SWEEPS_PER_ITER * (2 * k * 4) + k * 4
    flops_iter = SWEEPS_PER_ITER * (2 * k) + 16 * (2 * k)
    return {
        "bytes_per_edge_iter": bytes_iter,
        "flops_per_edge_iter": flops_iter,
        "sweeps_per_iter": SWEEPS_PER_ITER,
    }


FUSED_PATHS = ("csr_fused", "csr_fused_kb", "csr_ring_fused",
               "csr_ring_fused_kb", "csr_fused_2d", "csr_fused_2d_kb")


def roofline_model_fused(k: int) -> dict:
    """Fused-superstep cost model (ISSUE 13 satellite): bytes per
    directed edge WITHOUT the fd round-trip. The split model charges
    every sweep both endpoint rows because each sweep re-reads the
    HBM-resident gathered fd; the fused kernel DMAs each edge's dst row
    into VMEM exactly twice per iteration (grad phase + candidate phase
    — all 16 candidates reuse the VMEM-resident tile) and the src-side
    block plus the grad/F_new writes amortize to one row-read + one
    row-write equivalent per edge at real average degrees. hbm_frac for
    a fused run must quote THIS model — quoting the split model would
    overstate it ~10x (the honesty rule that added the sparse model in
    r11).
    """
    bytes_iter = 2 * (k * 4) + 2 * (k * 4)
    flops_iter = SWEEPS_PER_ITER * (2 * k) + 16 * (2 * k)
    return {
        "bytes_per_edge_iter": bytes_iter,
        "flops_per_edge_iter": flops_iter,
        "sweeps_per_iter": SWEEPS_PER_ITER,
        "variant": "fused",
    }


def roofline_model_fused_2d(k: int) -> dict:
    """2D fused-superstep cost model (ISSUE 17): the per-edge VMEM DMA
    traffic is the 1D fused model's (two dst-row DMAs per edge per
    iteration, src block + grad/F_new writes amortizing to one
    read + one write), PLUS one row-write equivalent per edge for the
    closure-buffer staging: the capped closure all_to_all lands the
    received rows in the compacted per-pair buffer the DMA descriptors
    then index, so each touched row is written once per iteration before
    the kernel reads it (at real average degrees the touched-row count
    is below the edge count, making one row per edge the honest upper
    bound — quoting the 1D fused model against a 2d run would overstate
    hbm_frac by ~20%)."""
    base = roofline_model_fused(k)
    return {
        **base,
        "bytes_per_edge_iter": base["bytes_per_edge_iter"] + k * 4,
        "variant": "fused_2d",
    }


def roofline_model_sparse(m: int) -> dict:
    """Sparse-representation cost model (ISSUE 7 satellite): bytes and
    FLOPs per directed edge scale with the top-M slot count, NOT K —
    quoting the dense model against a sparse run would overstate
    hbm_frac by K/M.

    bytes: a sparse row is M (int32 id, f32 weight) pairs = 8*M B; every
    sweep streams both endpoint rows (2*M*8), the grad sweep scatters one
    M-slot row back (M*4). flops: the merge lookup is a vmapped binary
    search (~M*log2(M) comparisons, counted as M*ceil(log2 M) flops)
    plus the M-length dot (2M) per visit; candidate construction
    clip(w + eta*g) adds 2M on the 16 candidate sweeps — the same form
    as the dense model with K -> M plus the search term.
    """
    import math

    logm = max(int(math.ceil(math.log2(max(m, 2)))), 1)
    bytes_iter = SWEEPS_PER_ITER * (2 * m * 8) + m * 4
    flops_iter = SWEEPS_PER_ITER * (2 * m + m * logm) + 16 * (2 * m)
    return {
        "bytes_per_edge_iter": bytes_iter,
        "flops_per_edge_iter": flops_iter,
        "sweeps_per_iter": SWEEPS_PER_ITER,
        "representation": "sparse",
        "sparse_m": m,
    }


def device_peaks(device_kind: str):
    """(hbm_gbs, bf16_tflops) for a device kind, or (None, None) when the
    chip is not in the table (CPU fallback, future TPUs)."""
    kind = (device_kind or "").lower()
    for sub, peaks in DEVICE_PEAKS.items():
        if sub in kind:
            return peaks
    return None, None


def roofline_position(
    eps: float, k: int, device_kind: str, sparse_m: int = 0,
    fused: bool = False, path: str = "",
) -> dict:
    """The artifact's roofline record for one config: the cost model, the
    achieved HBM-bandwidth fraction (`hbm_frac`) and MXU utilization
    (`mfu`), or None fractions off the peaks table. sparse_m > 0 selects
    the sparse cost model (bytes/FLOPs per edge ∝ M, not K); fused=True
    the fused-superstep model (no fd round-trip); a csr_fused_2d[_kb]
    `path` the 2d variant with the closure-buffer staging row — each
    keeps hbm_frac honest for its path."""
    if sparse_m:
        model = roofline_model_sparse(sparse_m)
    elif path.startswith("csr_fused_2d"):
        model = roofline_model_fused_2d(k)
    elif fused:
        model = roofline_model_fused(k)
    else:
        model = roofline_model(k)
    hbm_gbs, tflops = device_peaks(device_kind)
    achieved_gbs = eps * model["bytes_per_edge_iter"] / 1e9
    achieved_tflops = eps * model["flops_per_edge_iter"] / 1e12
    return {
        **model,
        "achieved_hbm_gbs": round(achieved_gbs, 1),
        "achieved_tflops": round(achieved_tflops, 4),
        "peak_hbm_gbs": hbm_gbs,
        "peak_bf16_tflops": tflops,
        "hbm_frac": (
            round(achieved_gbs / hbm_gbs, 4) if hbm_gbs else None
        ),
        "mfu": (
            round(achieved_tflops / tflops, 6) if tflops else None
        ),
    }

_T0 = time.perf_counter()


def _now() -> float:
    return time.perf_counter() - _T0


def time_windows(model, F0, windows, iters_per_window, warmup=WARMUP_ITERS):
    """Median edges/sec over `windows` timed windows + per-window records."""
    import jax

    state = model.init_state(F0)
    for _ in range(warmup):                 # compile + reach steady state
        state = model._step(state)
    jax.block_until_ready(state.F)
    recs = []
    e = model.g.num_directed_edges
    for _ in range(windows):
        t0 = _now()
        for _ in range(iters_per_window):
            state = model._step(state)
        jax.block_until_ready(state.F)
        t1 = _now()
        recs.append(
            {
                "eps": round(e * iters_per_window / (t1 - t0), 1),
                "t": [round(t0, 2), round(t1, 2)],
            }
        )
    med = statistics.median(r["eps"] for r in recs)
    return med, recs, float(state.llh)


def _backend_or_fallback(timeout_s: float = 180.0) -> str:
    """Initialize the JAX backend with a watchdog: a down accelerator
    tunnel makes jax.devices() hang FOREVER (observed: the axon relay,
    BENCH_r05), which would hang the whole scoreboard run.

    On a hang/crash the benchmark now RE-EXECS itself on the CPU platform
    instead of emitting a zero-value error record: the fallback run is
    clearly tagged ("backend": "cpu-fallback" in the output record) and
    runs a reduced config set, so the scoreboard gets a real (if slow)
    measurement plus the diagnosis rather than a zero. Re-exec, not
    in-process retry: the hung init thread may hold the backend-init lock
    forever. The zero-value error record remains only as the last resort
    when even the CPU re-exec cannot initialize."""
    import os
    import sys
    import threading

    out = {}

    def init():
        try:
            import jax

            out["backend"] = jax.default_backend()
        except BaseException as e:          # report crash distinctly below
            import traceback

            out["crash"] = repr(e)
            out["crash_tb"] = traceback.format_exc()

    t = threading.Thread(target=init, daemon=True)
    t.start()
    t.join(timeout_s)
    if "backend" in out:
        if os.environ.get(FALLBACK_ENV) == "1":
            return "cpu-fallback"
        return out["backend"]
    err = out.get(
        "crash",
        f"backend init hung > {timeout_s:.0f}s "
        "(accelerator tunnel down?)",
    )
    if "crash_tb" in out:         # full traceback for the run log
        print(out["crash_tb"], file=sys.stderr)
    if os.environ.get(FALLBACK_ENV) != "1":
        print(
            f"[bench] {err}; re-execing on JAX_PLATFORMS=cpu",
            file=sys.stderr,
        )
        sys.stderr.flush()
        os.execvpe(
            sys.executable,
            [sys.executable] + sys.argv,
            _fallback_child_env(os.environ),
        )
    print(
        json.dumps(
            {
                "metric": "edges/sec/chip",
                "value": 0,
                "unit": "edges/sec/chip",
                "vs_baseline": 0,
                "backend": "cpu-fallback",
                "error": err,
            }
        ),
        flush=True,       # os._exit skips stdio flush; a piped run
    )                     # would otherwise lose the diagnostic line
    sys.stderr.flush()
    os._exit(3)


def _open_telemetry():
    """Opt-in run telemetry (bigclam_tpu.obs): point BIGCLAM_TELEMETRY_DIR
    at a directory and the bench run leaves events.jsonl + run_report.json
    there — config stage timings, device-memory watermarks after each
    model build (the roofline's HBM model gets a measured counterpart),
    compile counts, and a stall heartbeat for hung backends."""
    tdir = os.environ.get("BIGCLAM_TELEMETRY_DIR")
    if not tdir:
        return None
    from bigclam_tpu.obs import RunTelemetry, install

    return install(
        RunTelemetry(tdir, entry="bench", heartbeat_s=600.0)
    )


def main() -> None:
    backend = _backend_or_fallback()
    cpu_fallback = backend == "cpu-fallback"
    tel = _open_telemetry()
    try:
        _main(backend, cpu_fallback)
    finally:
        if tel is not None:
            from bigclam_tpu.obs import uninstall

            tel.finalize()
            uninstall(tel)


def _main(backend, cpu_fallback) -> None:
    import jax

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.graph import build_graph
    from bigclam_tpu.models import BigClamModel
    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.spec import interpreter as spec

    from bigclam_tpu.utils.profiling import StageProfile

    prof = StageProfile()       # forwards stage events + memory watermarks
    on_tpu = jax.default_backend() == "tpu"   # into tel when installed
    configs = {}
    # cpu-fallback: a real (if slow) measurement beats a zero record, but
    # the big synthetic configs would take hours on a host CPU — keep the
    # headline config with fewer windows and record the rest as skipped
    windows = 2 if cpu_fallback else WINDOWS
    xla_windows = 2 if cpu_fallback else 3

    # --- Email-Enron K=100 (headline config), CSR vs XLA ---
    from bigclam_tpu.graph.store import is_cache_dir

    graph_source = "cache" if is_cache_dir(ENRON) else "text"
    t_load0 = time.perf_counter()
    g = build_graph(ENRON)
    graph_load_s = round(time.perf_counter() - t_load0, 3)
    cfg = BigClamConfig(num_communities=K_ENRON)
    rng = np.random.default_rng(0)
    F0 = rng.integers(0, 2, size=(g.num_nodes, K_ENRON)).astype(np.float64)

    with prof.stage("enron_csr"):
        model = BigClamModel(g, cfg, k_multiple=128)
        if on_tpu and model.engaged_path not in (
            "csr", "csr_grouped", "csr_fused", "csr_fused_kb",
        ):
            raise RuntimeError(
                "benchmark invalid: blocked-CSR kernels did not engage on "
                f"the TPU backend (path={model.engaged_path}, "
                f"reason: {model.path_reason})"
            )
        enron_eps, enron_windows, llh_last = time_windows(
            model, F0, windows, ITERS_PER_WINDOW
        )
    with prof.stage("enron_xla"):
        xla_model = BigClamModel(
            g, cfg.replace(use_pallas_csr=False, use_pallas=False),
            k_multiple=128,
        )
        enron_xla_eps, enron_xla_windows, _ = time_windows(
            xla_model, F0, xla_windows, ITERS_PER_WINDOW
        )
    kind = jax.devices()[0].device_kind
    configs["enron"] = {
        "config": f"Email-Enron N={g.num_nodes} 2E={g.num_directed_edges} "
                  f"K={K_ENRON}",
        "graph_source": graph_source,
        "graph_load_s": graph_load_s,
        "csr": {"eps": enron_eps, "path": model.engaged_path,
                "windows": enron_windows},
        "xla": {"eps": enron_xla_eps, "path": xla_model.engaged_path,
                "windows": enron_xla_windows},
        "csr_over_xla": round(enron_eps / enron_xla_eps, 2),
        "roofline": roofline_position(
            enron_eps, K_ENRON, kind,
            fused=model.engaged_path in FUSED_PATHS,
            path=model.engaged_path,
        ),
    }

    # --- representative grouped-path scale: AGM N=300K K=1000 ---
    if cpu_fallback:
        configs["large"] = {"skipped": "cpu-fallback (reduced run)"}
        configs["xl_k"] = {"skipped": "cpu-fallback (reduced run)"}
        configs["sparse"] = {"skipped": "cpu-fallback (reduced run)"}
        _ring_overlap_config(configs, jax, BigClamConfig,
                             sample_planted_graph)
        _emit(jax, spec, g, cfg, F0, backend, model, configs,
              enron_eps, llh_last)
        return
    with prof.stage("large"):
        gl, _ = sample_planted_graph(
            LARGE_N, LARGE_K, p_in=LARGE_P_IN, rng=np.random.default_rng(1)
        )
        cfg_l = BigClamConfig(num_communities=LARGE_K)
        Fl = np.random.default_rng(2).integers(
            0, 2, size=(gl.num_nodes, LARGE_K)
        ).astype(np.float64)
        model_l = BigClamModel(gl, cfg_l, k_multiple=128)
        if on_tpu and model_l.engaged_path not in (
            "csr", "csr_grouped", "csr_fused", "csr_fused_kb",
        ):
            raise RuntimeError(
                "benchmark invalid: large config fell back to "
                f"{model_l.engaged_path} ({model_l.path_reason})"
            )
        large_eps, large_windows, _ = time_windows(
            model_l, Fl, LARGE_WINDOWS, LARGE_ITERS_PER_WINDOW, warmup=2
        )
        xla_l = BigClamModel(
            gl, cfg_l.replace(use_pallas_csr=False, use_pallas=False),
            k_multiple=128,
        )
        large_xla_eps, large_xla_windows, _ = time_windows(
            xla_l, Fl, 2, LARGE_ITERS_PER_WINDOW, warmup=1
        )
    configs["large"] = {
        "config": f"AGM planted N={gl.num_nodes} "
                  f"2E={gl.num_directed_edges} K={LARGE_K}",
        "csr": {"eps": large_eps, "path": model_l.engaged_path,
                "windows": large_windows},
        "xla": {"eps": large_xla_eps, "path": xla_l.engaged_path,
                "windows": large_xla_windows},
        "csr_over_xla": round(large_eps / large_xla_eps, 2),
        "roofline": roofline_position(
            large_eps, LARGE_K, kind,
            fused=model_l.engaged_path in FUSED_PATHS,
            path=model_l.engaged_path,
        ),
    }

    # --- K-blocked regime: AGM N=60K K=3000 (csr_grouped_kb vs XLA) ---
    # newest kernel path (round 4): contained — a Mosaic refusal here is
    # RECORDED in the artifact instead of taking down the headline configs
    try:
        gk, _ = sample_planted_graph(
            XLK_N, XLK_K, p_in=XLK_P_IN, rng=np.random.default_rng(3)
        )
        cfg_k = BigClamConfig(num_communities=XLK_K)
        Fk = np.random.default_rng(4).integers(
            0, 2, size=(gk.num_nodes, XLK_K)
        ).astype(np.float64)
        model_k = BigClamModel(gk, cfg_k, k_multiple=128)
        if on_tpu and model_k.engaged_path not in (
            "csr_grouped_kb", "csr_fused_kb",
        ):
            raise RuntimeError(
                "K-blocked config fell back to "
                f"{model_k.engaged_path} ({model_k.path_reason})"
            )
        xlk_eps, xlk_windows, _ = time_windows(
            model_k, Fk, 2, LARGE_ITERS_PER_WINDOW, warmup=1
        )
        xla_k = BigClamModel(
            gk, cfg_k.replace(use_pallas_csr=False, use_pallas=False),
            k_multiple=128,
        )
        xlk_xla_eps, xlk_xla_windows, _ = time_windows(
            xla_k, Fk, 2, LARGE_ITERS_PER_WINDOW, warmup=1
        )
        configs["xl_k"] = {
            "config": f"AGM planted N={gk.num_nodes} "
                      f"2E={gk.num_directed_edges} K={XLK_K}",
            "csr": {"eps": xlk_eps, "path": model_k.engaged_path,
                    "windows": xlk_windows},
            "xla": {"eps": xlk_xla_eps, "path": xla_k.engaged_path,
                    "windows": xlk_xla_windows},
            "csr_over_xla": round(xlk_eps / xlk_xla_eps, 2),
            "roofline": roofline_position(
                xlk_eps, XLK_K, kind,
                fused=model_k.engaged_path in FUSED_PATHS,
                path=model_k.engaged_path,
            ),
        }
    except Exception as e:           # noqa: BLE001 — recorded, not silent
        configs["xl_k"] = {"error": f"{type(e).__name__}: {e}"}

    # --- sparse top-M representation at the large-K config (ISSUE 7) ---
    # same graph + K as "large", affiliation state in top-M member lists:
    # the eps ratio against large's XLA run shows what M-not-K bytes/edge
    # buys, and the roofline uses the SPARSE cost model so hbm_frac is
    # quoted against the bytes the path actually moves
    try:
        from bigclam_tpu.models import SparseBigClamModel

        sparse_m = 64
        cfg_s = cfg_l.replace(
            representation="sparse", sparse_m=sparse_m,
            use_pallas_csr=False, use_pallas=False,
        )
        model_s = SparseBigClamModel(gl, cfg_s)
        sparse_eps, sparse_windows, _ = time_windows(
            model_s, Fl, 2, LARGE_ITERS_PER_WINDOW, warmup=1
        )
        configs["sparse"] = {
            "config": f"AGM planted N={gl.num_nodes} "
                      f"2E={gl.num_directed_edges} K={LARGE_K} "
                      f"M={model_s.m} (sparse top-M)",
            "representation": "sparse",
            "sparse_m": model_s.m,
            "sparse": {"eps": sparse_eps, "path": model_s.engaged_path,
                       "windows": sparse_windows},
            "sparse_over_xla": round(sparse_eps / large_xla_eps, 2),
            "affiliation_state_bytes": model_s.state_nbytes(),
            "affiliation_state_bytes_dense": gl.num_nodes * LARGE_K * 4,
            "roofline": roofline_position(
                sparse_eps, LARGE_K, kind, sparse_m=model_s.m
            ),
        }
    except Exception as e:           # noqa: BLE001 — recorded, not silent
        configs["sparse"] = {"error": f"{type(e).__name__}: {e}"}

    _ring_overlap_config(configs, jax, BigClamConfig, sample_planted_graph)
    _emit(jax, spec, g, cfg, F0, backend, model, configs, enron_eps,
          llh_last)


def _ring_overlap_config(configs, jax, BigClamConfig, sample_planted_graph):
    """Ring schedule, overlapped vs serialized rotations: edges/sec/chip
    under both schedules + the comm-hidden fraction (the double-buffered
    ppermute win; utils.profiling.overlap_report is the shared hook).
    Needs >= 2 devices — the ring is a collective schedule. Contained like
    xl_k: a failure is recorded in the artifact, not fatal."""
    ndev = len(jax.devices())
    if ndev < 2:
        configs["ring_overlap"] = {"skipped": f"single device (ndev={ndev})"}
        return
    try:
        from bigclam_tpu.parallel import RingBigClamModel, make_mesh
        from bigclam_tpu.utils.profiling import overlap_report

        dp = min(8, ndev)
        n = RING_PER_SHARD * dp
        gr, _ = sample_planted_graph(
            n, max(n // 256, 2), p_in=0.15, rng=np.random.default_rng(5)
        )
        cfg_r = BigClamConfig(num_communities=RING_K)
        mesh = make_mesh((dp, 1), jax.devices()[:dp])
        # balance=True: the planted fixture is locality-ordered — the
        # ring's bucket-padding worst case; relabeled is how a real
        # deployment runs it (and it mutes the imbalance warning)
        model_r = RingBigClamModel(gr, cfg_r, mesh, balance=True)
        Fr = np.random.default_rng(6).uniform(
            0.1, 1.0, size=(gr.num_nodes, RING_K)
        )
        state_r = model_r.init_state(Fr)
        rep = overlap_report(
            model_r, state_r, steps=RING_STEPS, warmup=1
        )
        e = gr.num_directed_edges
        eps_chip = {
            k: round(e / v / dp, 1)
            for k, v in rep["sec_per_step"].items()
        }
        # collective-traffic accounting (obs.comms, ISSUE 10): modeled
        # bytes/step of the compiled ring step next to hbm_frac, plus
        # the same model re-priced from the LIVE device buffers — the
        # pair the comms gate reconciles; drift = a layout change moved
        # more bytes than the model admits
        cm = model_r.comms
        measured = model_r.comms_measured(state_r)
        configs["ring_overlap"] = {
            "config": f"AGM planted N={gr.num_nodes} 2E={e} K={RING_K} "
                      f"dp={dp} (ring, balanced)",
            "path": model_r.engaged_path,
            "eps_per_chip": eps_chip,
            "sec_per_step": rep["sec_per_step"],
            "comm_hidden_fraction": rep["comm_hidden_fraction"],
            "comms": {
                "modeled_bytes_per_step": round(cm.bytes_per_step(), 1),
                "measured_bytes_per_step": round(
                    measured.bytes_per_step(), 1
                ),
                "rotation_bytes_per_step": cm.site_bytes().get(
                    "ring/ppermute_F_rot"
                ),
            },
            "roofline": roofline_position(
                eps_chip["overlap"], RING_K,
                jax.devices()[0].device_kind,
            ),
        }
    except Exception as e:           # noqa: BLE001 — recorded, not silent
        configs["ring_overlap"] = {"error": f"{type(e).__name__}: {e}"}


def _emit(jax, spec, g, cfg, F0, backend, model, configs, enron_eps,
          llh_last) -> None:
    """Oracle baseline + the one-line JSON record (shared by the normal and
    the cpu-fallback run)."""
    # --- oracle baseline: exact-semantics iterations on host CPU ---
    base_times = []
    for _ in range(BASELINE_ITERS):
        Fb = F0.copy()
        sb = Fb.sum(0)
        t0 = time.perf_counter()
        spec.line_search_step(Fb, sb, g, cfg)
        base_times.append(time.perf_counter() - t0)
    base_eps = g.num_directed_edges / statistics.median(base_times)

    record = {
        "metric": "edges/sec/chip",
        "value": enron_eps,
        "unit": "edges/sec/chip",
        "vs_baseline": round(enron_eps / base_eps, 2),
        "path": model.engaged_path,
        # headline runs the dense reference representation; the sparse
        # top-M measurement lives in configs["sparse"] with its own
        # bytes/edge model
        "representation": "dense",
        # node-axis partition identity (ISSUE 16): part of the perf
        # ledger's match key — a 2d record never baselines against 1d
        "partition": getattr(cfg, "partition", "1d"),
        "backend": backend,
        "config": configs["enron"]["config"],
        "graph_source": configs["enron"].get("graph_source"),
        "configs": configs,
        # headline roofline position (VERDICT r5 Next #5): the
        # denominator for edges/sec/chip — fraction of this
        # chip's HBM bandwidth and MXU peak the headline config
        # achieves under the stated bytes/flops-per-edge model
        "roofline": configs["enron"].get("roofline"),
        "baseline_spec_eps": round(base_eps, 1),
        "baseline_iters_sec": [round(t, 3) for t in base_times],
        "iters_per_window": ITERS_PER_WINDOW,
        "sec_per_iter": round(g.num_directed_edges / enron_eps, 4),
        "device": str(jax.devices()[0]),
        # TrainState.llh is the LLH of the step's INPUT F, so this
        # is the last *evaluated* LLH (one update behind state.F)
        "llh_at_last_eval": llh_last,
    }
    # memory accounting (obs.memory, ISSUE 12): the headline model's
    # modeled per-device HBM next to the allocator's measured peak
    # (None on CPU backends — memory_stats is TPU-only), stamped into
    # the artifact AND the telemetry final record the roofline's
    # hbm_frac rides, so the bandwidth model and the capacity model can
    # never silently disagree about what was resident
    from bigclam_tpu.obs import telemetry as _obs

    tel = _obs.current()
    mem = getattr(model, "memory", None)
    record["hbm_modeled_bytes"] = (
        round(mem.hbm_bytes(), 1) if mem is not None else None
    )
    measured_peak = None
    if tel is not None:
        for stats in tel.device_peak.values():
            v = stats.get("peak_bytes_in_use") or stats.get("bytes_in_use")
            if isinstance(v, (int, float)) and (
                measured_peak is None or v > measured_peak
            ):
                measured_peak = v
    record["hbm_peak_measured_bytes"] = measured_peak
    if tel is not None:
        roof = record.get("roofline") or {}
        tel.set_final(
            {
                "hbm_modeled_bytes": record["hbm_modeled_bytes"],
                "hbm_peak_measured_bytes": measured_peak,
                "metric": record["metric"],
                "value": record["value"],
                "vs_baseline": record["vs_baseline"],
                "path": record["path"],
                "backend": record["backend"],
                # workload identity for the perf ledger: the headline
                # metric's graph (BIGCLAM_BENCH_GRAPH can swap it — two
                # bench runs over different graphs must never baseline
                # against each other)
                "n": g.num_nodes,
                "edges": g.num_directed_edges // 2,
                "representation": record["representation"],
                "partition": record["partition"],
                # the ledger's roofline fields (obs.ledger): hbm_frac is
                # the denominator "is it actually fast" gates against —
                # with the VARIANT of the cost model it was quoted
                # against (a fused run quoted on the split model would
                # overstate hbm_frac ~10x, ISSUE 13)
                "hbm_frac": roof.get("hbm_frac"),
                "mfu": roof.get("mfu"),
                "roofline_variant": roof.get("variant", "split"),
                "bytes_per_edge_iter": roof.get("bytes_per_edge_iter"),
                # comms-observability fields (ISSUE 10): the ring
                # config's overlap fraction is VERDICTED by `cli perf
                # diff` (rotation hops falling out of overlap is a
                # regression even at flat single-chip step time); the
                # modeled bytes/step rides the comms events the ring
                # build already emitted into this telemetry run
                "overlap_frac": (
                    configs.get("ring_overlap", {}) or {}
                ).get("comm_hidden_fraction"),
            }
        )
    print(json.dumps(record))


if __name__ == "__main__":
    main()
