"""Headline benchmark: BigCLAM optimizer throughput on Email-Enron, K=100
(BASELINE config 2), on the available accelerator.

Prints ONE JSON line:
  {"metric": "edges/sec/chip", "value": N, "unit": "edges/sec/chip",
   "vs_baseline": R, "path": "csr|csr_grouped|pallas_vmem|xla", ...}

metric: directed-edge traversals of the graph per second per chip, counting
one optimizer iteration as ONE traversal of the 2E directed edges (each
iteration internally performs 17 fused sweeps — 1 gradient/LLH + 16 Armijo
candidates — so multiply by 17 for raw gather-dot throughput).

value: the MEDIAN over several timing windows (a single window is vulnerable
to cold-chip / background-noise artifacts: round 1 recorded 7.66M on a run
that steady-states at 27M). "windows_eps" carries every window so outliers
are visible; "path" asserts which kernel implementation actually ran — on a
TPU backend the blocked-CSR kernels MUST have engaged, a silent XLA fallback
fails the run rather than polluting the scoreboard.

vs_baseline: speedup over the float64 NumPy spec interpreter (the exact
reference semantics, SURVEY.md §4.2) running the same iteration on this
host's CPU — the reference itself publishes no numbers (BASELINE.md), so the
oracle's single-core throughput is the anchor; it is re-measured here (one
iteration) for comparability.
"""

import json
import statistics
import time

import numpy as np

ENRON = "/root/reference/data/Email-Enron.txt"
K = 100
WINDOWS = 5
ITERS_PER_WINDOW = 10
WARMUP_ITERS = 3


def main() -> None:
    import jax

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.graph import build_graph
    from bigclam_tpu.models import BigClamModel
    from bigclam_tpu.spec import interpreter as spec

    g = build_graph(ENRON)
    cfg = BigClamConfig(num_communities=K)
    rng = np.random.default_rng(0)
    F0 = rng.integers(0, 2, size=(g.num_nodes, K)).astype(np.float64)

    # --- accelerator run (float32, K padded to the 128-lane boundary) ---
    model = BigClamModel(g, cfg, k_multiple=128)
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu and model.engaged_path not in ("csr", "csr_grouped"):
        raise RuntimeError(
            "benchmark invalid: blocked-CSR kernels did not engage on the "
            f"TPU backend (path={model.engaged_path}, "
            f"reason: {model.path_reason})"
        )
    state = model.init_state(F0)
    for _ in range(WARMUP_ITERS):           # compile + reach steady state
        state = model._step(state)
    jax.block_until_ready(state.F)
    window_eps = []
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        for _ in range(ITERS_PER_WINDOW):
            state = model._step(state)
        jax.block_until_ready(state.F)
        dt = time.perf_counter() - t0
        window_eps.append(g.num_directed_edges * ITERS_PER_WINDOW / dt)
    n_chips = 1                             # single-chip benchmark config
    edges_per_sec = statistics.median(window_eps) / n_chips

    # --- oracle baseline: one exact-semantics iteration on host CPU ---
    Fb = F0.copy()
    sb = Fb.sum(0)
    t0 = time.perf_counter()
    spec.line_search_step(Fb, sb, g, cfg)
    base_dt = time.perf_counter() - t0
    base_edges_per_sec = g.num_directed_edges / base_dt

    print(
        json.dumps(
            {
                "metric": "edges/sec/chip",
                "value": round(edges_per_sec, 1),
                "unit": "edges/sec/chip",
                "vs_baseline": round(edges_per_sec / base_edges_per_sec, 2),
                "path": model.engaged_path,
                "config": f"Email-Enron N={g.num_nodes} 2E={g.num_directed_edges} K={K}",
                "windows_eps": [round(x, 1) for x in window_eps],
                "iters_per_window": ITERS_PER_WINDOW,
                "sec_per_iter": round(
                    g.num_directed_edges / edges_per_sec, 4
                ),
                "device": str(jax.devices()[0]),
                # TrainState.llh is the LLH of the step's INPUT F, so this is
                # the last *evaluated* LLH (one update behind state.F)
                "llh_at_last_eval": float(state.llh),
            }
        )
    )


if __name__ == "__main__":
    main()
