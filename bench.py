"""Headline benchmark: BigCLAM optimizer throughput on Email-Enron, K=100
(BASELINE config 2), on the available accelerator.

Prints ONE JSON line:
  {"metric": "edges/sec/chip", "value": N, "unit": "edges/sec/chip",
   "vs_baseline": R, ...}

metric: directed-edge traversals of the graph per second per chip, counting
one optimizer iteration as ONE traversal of the 2E directed edges (each
iteration internally performs 17 fused sweeps — 1 gradient/LLH + 16 Armijo
candidates — so multiply by 17 for raw gather-dot throughput).

vs_baseline: speedup over the float64 NumPy spec interpreter (the exact
reference semantics, SURVEY.md §4.2) running the same iteration on this
host's CPU — the reference itself publishes no numbers (BASELINE.md), so the
oracle's single-core throughput is the anchor; it is re-measured here (one
iteration) for comparability.
"""

import json
import time

import numpy as np

ENRON = "/root/reference/data/Email-Enron.txt"
K = 100
TIMED_ITERS = 10


def main() -> None:
    import jax

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.graph import build_graph
    from bigclam_tpu.models import BigClamModel
    from bigclam_tpu.spec import interpreter as spec

    g = build_graph(ENRON)
    cfg = BigClamConfig(num_communities=K)
    rng = np.random.default_rng(0)
    F0 = rng.integers(0, 2, size=(g.num_nodes, K)).astype(np.float64)

    # --- accelerator run (float32, K padded to the 128-lane boundary) ---
    model = BigClamModel(g, cfg, k_multiple=128)
    state = model.init_state(F0)
    state = model._step(state)                 # warmup / compile
    jax.block_until_ready(state.F)
    t0 = time.perf_counter()
    for _ in range(TIMED_ITERS):
        state = model._step(state)
    jax.block_until_ready(state.F)
    dt = time.perf_counter() - t0
    n_chips = 1                                # single-chip benchmark config
    edges_per_sec = g.num_directed_edges * TIMED_ITERS / dt / n_chips

    # --- oracle baseline: one exact-semantics iteration on host CPU ---
    Fb = F0.copy()
    sb = Fb.sum(0)
    t0 = time.perf_counter()
    spec.line_search_step(Fb, sb, g, cfg)
    base_dt = time.perf_counter() - t0
    base_edges_per_sec = g.num_directed_edges / base_dt

    print(
        json.dumps(
            {
                "metric": "edges/sec/chip",
                "value": round(edges_per_sec, 1),
                "unit": "edges/sec/chip",
                "vs_baseline": round(edges_per_sec / base_edges_per_sec, 2),
                "config": f"Email-Enron N={g.num_nodes} 2E={g.num_directed_edges} K={K}",
                "iters_timed": TIMED_ITERS,
                "sec_per_iter": round(dt / TIMED_ITERS, 4),
                "device": str(jax.devices()[0]),
                # TrainState.llh is the LLH of the step's INPUT F, so this is
                # the last *evaluated* LLH (one update behind state.F)
                "llh_at_last_eval": float(state.llh),
            }
        )
    )


if __name__ == "__main__":
    main()
