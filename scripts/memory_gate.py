"""Memory-accounting gate (ISSUE 12): prove, on CPU fakes, that the
static HBM/host-RSS models, the live reconciliation, and the preflight
verdicts do what they claim — deterministically — and cost nothing on
the trajectory.

Six check groups, the ISSUE 12 acceptance criteria verbatim:

  model_vs_live   the static per-device HBM model baked at step build
                  EQUALS the live addressable-shard byte sum (drift 0 —
                  exact, not banded, on the CPU fake) for all four
                  trainer families: dense single-chip (XLA + CSR
                  interpret), all-gather sharded (dp 2 and 4, tp 2),
                  ring, and sparse (single-chip + sharded), across
                  rollback on/off
  leak            a planted retained buffer (an F-sized copy the model
                  does not know) fires EXACTLY the memory_drift
                  anomaly; the clean reconcile fires none
  preflight       `cli preflight` (jax-free in-process) returns the
                  correct fits/doesn't verdict: an over-sized dense
                  config against a fake device limit exits 2 naming
                  hbm as binding, the same config relaxed with
                  --representation sparse exits 0
  perf diff       `cli perf diff` exits 2 on an injected
                  hbm_modeled_bytes regression and 0 on the identical
                  re-run
  identity        accounting-on trajectories are bit-identical to
                  accounting-off (the model is host-side arithmetic at
                  build time — it never touches the math)
  overhead        the per-iteration observability path stays within
                  the existing < 2% pin (the memory layer added no
                  per-iteration work; the heartbeat-cadence watermark
                  rides the watchdog thread)

    python scripts/memory_gate.py [MEM_r16.json]

Exit 0 iff every check passes.
"""

import json
import os
import sys
import tempfile
import time
import warnings

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else None

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    from bigclam_tpu.utils.dist import request_cpu_devices

    request_cpu_devices(8)

    import jax.numpy as jnp

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.models import BigClamModel, SparseBigClamModel
    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.obs import (
        RunTelemetry,
        install,
        uninstall,
        validate_events_file,
    )
    from bigclam_tpu.obs import ledger as L
    from bigclam_tpu.obs.report import load_events
    from bigclam_tpu.obs.telemetry import EVENTS_NAME
    from bigclam_tpu.parallel import (
        RingBigClamModel,
        ShardedBigClamModel,
        SparseShardedBigClamModel,
        make_mesh,
    )

    checks = {}
    detail = {}

    g, _ = sample_planted_graph(
        256, 4, p_in=0.3, rng=np.random.default_rng(0)
    )
    F0 = np.random.default_rng(1).uniform(0.1, 1.0, size=(g.num_nodes, 4))

    def base_cfg(**kw):
        d = dict(num_communities=4, dtype="float64", max_iters=6,
                 conv_tol=0.0, health_every=1)
        d.update(kw)
        return BigClamConfig(**d)

    # --- 1. modeled == live addressable bytes, EXACT, four families --
    recons = {}

    def exact(name, model, state):
        r = model.memory_reconcile(state, emit=False)
        recons[name] = {
            "modeled_bytes": r["modeled_bytes"],
            "measured_bytes": r["measured_bytes"],
            "drift_frac": r["drift_frac"],
            "hbm_modeled_bytes": r["hbm_modeled_bytes"],
        }
        checks[f"exact_{name}"] = (
            r["modeled_bytes"] == r["measured_bytes"]
            and r["drift_frac"] == 0.0
        )

    for rollback in (0, 3):
        tag = f"_rb{rollback}" if rollback else ""
        m = BigClamModel(g, base_cfg(rollback_budget=rollback))
        st = m._step(m.init_state(F0))
        exact(f"dense{tag}", m, st)
    mc = BigClamModel(g, base_cfg(
        dtype="float32", use_pallas_csr=True, pallas_interpret=True,
        csr_block_b=64, csr_tile_t=64,
    ))
    exact("dense_csr", mc, mc._step(mc.init_state(F0)))
    for dp in (2, 4):
        mesh = make_mesh((dp, 1), jax.devices()[:dp])
        ms = ShardedBigClamModel(g, base_cfg(), mesh)
        exact(f"sharded_dp{dp}", ms, ms._step(ms.init_state(F0)))
    mesh22 = make_mesh((2, 2), jax.devices()[:4])
    mtp = ShardedBigClamModel(g, base_cfg(), mesh22)
    exact("sharded_tp2", mtp, mtp._step(mtp.init_state(F0)))
    mesh2 = make_mesh((2, 1), jax.devices()[:2])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        mr = RingBigClamModel(g, base_cfg(), mesh2, balance=False)
    exact("ring_dp2", mr, mr._step(mr.init_state(F0)))
    K = 64
    F0w = np.zeros((g.num_nodes, K))
    F0w[:, :4] = F0
    cfg_sp = base_cfg(num_communities=K, representation="sparse",
                      sparse_m=8, sparse_comm_cap=16, max_iters=4)
    msp = SparseBigClamModel(g, cfg_sp)
    exact("sparse", msp, msp._step(msp.init_state(F0w)))
    mss = SparseShardedBigClamModel(g, cfg_sp, mesh2)
    exact("sparse_sharded_dp2", mss, mss._step(mss.init_state(F0w)))

    # --- 2. planted retained buffer -> exactly the drift anomaly -----
    work = tempfile.mkdtemp(prefix="memory_gate_")
    leak_dir = os.path.join(work, "leak")
    tel = install(RunTelemetry(leak_dir, entry="fit", quiet=True))
    try:
        ml = BigClamModel(g, base_cfg())
        stl = ml.init_state(F0)
        clean = ml.memory_reconcile(stl)
        leak = jnp.array(np.asarray(stl.F))
        planted = ml.memory_reconcile(stl, extra=[leak])
        tel.finalize()
    finally:
        uninstall(tel)
    anomalies = [
        e for e in (load_events(leak_dir) or [])
        if e.get("kind") == "anomaly"
    ]
    detail["leak"] = {
        "clean_drift": clean["drift_frac"],
        "planted_drift": planted["drift_frac"],
        "anomalies": [
            {k: e.get(k) for k in ("check", "drift_frac")}
            for e in anomalies
        ],
    }
    checks["clean_reconcile_fires_nothing"] = clean["ok"]
    checks["leak_fires_exactly_drift_anomaly"] = (
        not planted["ok"]
        and len(anomalies) == 1
        and anomalies[0]["check"] == "memory_drift"
    )
    _, schema_errors = validate_events_file(
        os.path.join(leak_dir, EVENTS_NAME)
    )
    checks["events_schema_valid"] = not schema_errors

    # --- 3. preflight verdicts (jax-free CLI, in-process) ------------
    from bigclam_tpu.cli import main as cli_main
    from bigclam_tpu.graph.store import compile_graph_cache

    text = os.path.join(work, "g.txt")
    with open(text, "w") as f:
        src, dst = g.src, g.dst
        for u, v in zip(src, dst):
            if u < v:
                f.write(f"{u}\t{v}\n")
    cache = os.path.join(work, "g.cache")
    compile_graph_cache(text, cache, num_shards=4)
    common = ["preflight", "--graph", cache, "--k", "2048",
              "--mesh", "4,1", "--hbm-bytes", str(4 << 20)]
    rc_over = cli_main(common)
    rc_relaxed = cli_main(
        common + ["--representation", "sparse", "--sparse-m", "16"]
    )
    detail["preflight"] = {"over_rc": rc_over, "relaxed_rc": rc_relaxed}
    checks["preflight_flags_oversized"] = rc_over == 2
    checks["preflight_passes_sparse_relaxed"] = rc_relaxed == 0

    # --- 4. perf diff on injected hbm regression ---------------------
    from bigclam_tpu.utils.profiling import StageProfile

    def run_fit(tag):
        tdir = os.path.join(work, tag)
        t = install(RunTelemetry(tdir, entry="fit", quiet=True))
        try:
            mdl = ShardedBigClamModel(g, base_cfg(max_iters=8), mesh2)
            with StageProfile().stage("fit"):
                res = mdl.fit(F0)
            t.set_final({
                "llh": res.llh, "iters": res.num_iters,
                "n": g.num_nodes, "edges": g.num_edges, "k": 4,
                "mesh": "2x1",
                "hbm_modeled_bytes": round(mdl.memory.hbm_bytes(), 1),
            })
            rep = t.finalize()
        finally:
            uninstall(t)
        return tdir, rep, res

    a_dir, a_rep, a_res = run_fit("baseline")
    a_events = load_events(a_dir) or []
    secs = [e["sec_per_iter"] for e in a_events
            if e.get("kind") == "step"
            and isinstance(e.get("sec_per_iter"), (int, float))]
    base_rec = L.build_record(a_rep, secs or [0.01] * 10)
    checks["record_carries_hbm"] = isinstance(
        base_rec.get("hbm_modeled_bytes"), float
    ) and base_rec["hbm_modeled_bytes"] > 0
    checks["record_carries_host_rss"] = isinstance(
        base_rec.get("host_rss_modeled_bytes"), float
    ) and base_rec["host_rss_modeled_bytes"] > 0
    ledger_path = os.path.join(work, "ledger.jsonl")
    led = L.PerfLedger(ledger_path)
    led.append(base_rec)
    led.append(dict(base_rec, run="rerun", ts=base_rec["ts"] + 1))
    rc_same = cli_main(["perf", "diff", "--ledger", ledger_path])
    checks["perf_diff_passes_identical"] = rc_same == 0
    led.append(dict(
        base_rec, run="injected-hbm", ts=base_rec["ts"] + 2,
        hbm_modeled_bytes=round(base_rec["hbm_modeled_bytes"] * 2.0, 1),
    ))
    rc_inj = cli_main(["perf", "diff", "--ledger", ledger_path])
    checks["perf_diff_flags_injected_hbm"] = rc_inj == 2
    detail["perf_diff"] = {"identical_rc": rc_same, "injected_rc": rc_inj}

    # --- 5. accounting-on bit-identity -------------------------------
    off_res = ShardedBigClamModel(
        g, base_cfg(max_iters=8), mesh2
    ).fit(F0)
    checks["accounting_on_bit_identical"] = bool(
        np.array_equal(a_res.F, off_res.F)
        and a_res.llh_history == off_res.llh_history
    )

    # --- 6. per-iteration observability overhead < 2% ----------------
    from bigclam_tpu.obs import trace as obs_trace
    from bigclam_tpu.utils.profiling import step_time

    g_big, _ = sample_planted_graph(
        4000, 16, p_in=0.2, rng=np.random.default_rng(3)
    )
    big = BigClamModel(g_big, base_cfg(num_communities=16, max_iters=2,
                                       health_every=10))
    Fb = np.random.default_rng(4).uniform(
        0.1, 1.0, size=(g_big.num_nodes, 16)
    )
    sec_per_step = step_time(big._step, big.init_state(Fb), steps=10,
                             warmup=2)
    t = install(RunTelemetry(os.path.join(work, "ovh"), entry="fit",
                             quiet=True))
    try:
        iters = 3000
        t0 = time.perf_counter()
        for i in range(iters):
            with obs_trace.span("fit_loop/dispatch", emit=False):
                pass
            with obs_trace.span("fit_loop/sync", emit=False):
                pass
            with obs_trace.span("fit_loop/callback", emit=False):
                pass
            t.step_beat(i, -1.0)
        per_iter = (time.perf_counter() - t0) / iters
        t.finalize()
    finally:
        uninstall(t)
    detail["overhead"] = {
        "sec_per_step": round(sec_per_step, 6),
        "obs_path_per_iter": round(per_iter, 9),
        "fraction": round(per_iter / sec_per_step, 6),
    }
    checks["overhead_under_2pct"] = per_iter < 0.02 * sec_per_step

    ok = all(checks.values())
    artifact = {
        "gate": "memory_r16",
        "created_unix": round(time.time(), 1),
        "pass": ok,
        "checks": checks,
        "reconciliations": recons,
        "detail": detail,
        "device": str(jax.devices()[0]),
        "jax": jax.__version__,
        "note": (
            "static per-device HBM model == live addressable-shard "
            "bytes EXACTLY (drift 0) across dense(XLA/CSR/rollback), "
            "sharded dp2/dp4/tp2, ring, sparse single+sharded; a "
            "planted retained F copy fires exactly one memory_drift "
            "anomaly; cli preflight exits 2 on an over-sized dense "
            "config vs a 4 MiB fake device limit and 0 with "
            "--representation sparse; cli perf diff exit 2 on 2x "
            "injected hbm_modeled_bytes, exit 0 on the identical "
            "re-run; accounting-on trajectories bit-identical; "
            "per-iteration obs path within the existing <2% pin."
        ),
    }
    line = json.dumps(artifact, sort_keys=True)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    if not ok:
        bad = sorted(k for k, v in checks.items() if not v)
        print(f"FAILED checks: {bad}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
