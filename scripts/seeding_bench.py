"""Seeding-at-scale throughput measurement (VERDICT round-3 item 5).

Beyond the 16,384-node dense-device bound, conductance seeding runs on the
host (native C++ OpenMP capped estimator, NumPy fallback). This script
makes that pass a BUDGETED cost instead of an unmeasured one: it builds a
>= 100M-directed-edge synthetic graph with a heavy-tailed hub component
(so the degree cap actually binds), times every stage of the seeding
pipeline — capped triangle counts, conductance closed forms, locally-
minimal ranking — and journals one JSON line.

    python scripts/seeding_bench.py [n_nodes] [n_edges_millions] [out.json]

Defaults: N=10M nodes, 50M undirected edges (100M directed), cap=64.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_synthetic(n: int, m_edges: int, rng: np.random.Generator):
    """Uniform pairs + a hub component: 5% of edges touch a small hot set,
    giving hub degrees far above any practical cap."""
    from bigclam_tpu.graph.ingest import graph_from_edges

    m_uniform = int(m_edges * 0.95)
    m_hub = m_edges - m_uniform
    hubs = max(n // 1000, 1)
    src = rng.integers(0, n, size=m_edges, dtype=np.int64)
    dst = np.empty(m_edges, dtype=np.int64)
    dst[:m_uniform] = rng.integers(0, n, size=m_uniform, dtype=np.int64)
    dst[m_uniform:] = rng.integers(0, hubs, size=m_hub, dtype=np.int64)
    keep = src != dst
    edges = np.stack([src[keep], dst[keep]], axis=1)
    return graph_from_edges(edges, num_nodes=n)


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    m_m = float(sys.argv[2]) if len(sys.argv) > 2 else 50.0
    out_path = sys.argv[3] if len(sys.argv) > 3 else None
    cap = 64

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.ops import seeding

    rng = np.random.default_rng(0)
    t0 = time.time()
    g = build_synthetic(n, int(m_m * 1e6), rng)
    t_build = time.time() - t0
    e = g.num_directed_edges

    try:
        from bigclam_tpu.graph.native import triangle_counts_capped  # noqa
        backend = "native-openmp"
    except ImportError:
        backend = "numpy"

    t0 = time.time()
    tri = seeding.triangle_counts_sampled(
        g, cap, np.random.default_rng(1)
    )
    t_tri = time.time() - t0

    # the counting stage dominates; hand the precomputed tri to the
    # closed forms instead of running the pass a second time
    t0 = time.time()
    phi = seeding.conductance(g, backend="sampled", degree_cap=cap, tri=tri)
    t_phi = t_tri + (time.time() - t0)

    cfg = BigClamConfig(seeding_degree_cap=cap)
    t0 = time.time()
    seeds = seeding.rank_seeds(g, phi, cfg)
    t_rank = time.time() - t0

    # quality mode's covering walk at a Friendster-class K: the order prep
    # (rank + lexsort, shared with rank_seeds' cost profile) and the
    # greedy walk itself (native when the .so built) timed separately
    k_cover = 25_000
    t0 = time.time()
    order = seeding.covering_order(g, phi, cfg)
    t_order = time.time() - t0
    t0 = time.time()
    cover = seeding.select_seeds_covering(
        g, phi, k_cover, cfg, hops=2, order=order
    )
    t_cover = time.time() - t0

    # device backend (C5 past the dense bound): same splitmix sampler, so
    # the estimates must agree with the host backends
    import jax

    t_dev = None
    dev_agrees = None
    if jax.default_backend() == "tpu":
        seed = int(np.random.default_rng(1).integers(2**63))
        # first call pays the jit compile; time the warm second call so the
        # journal tracks throughput, not compile-time drift
        tri_dev = seeding.triangle_counts_sampled_device(g, cap, seed)
        t0 = time.time()
        tri_dev = seeding.triangle_counts_sampled_device(g, cap, seed)
        t_dev = time.time() - t0
        dev_agrees = bool(np.allclose(tri_dev, tri, rtol=1e-4, atol=1e-4))

    rec = {
        "bench": "seeding-at-scale",
        "config": f"synthetic N={g.num_nodes} 2E={e} "
                  f"max_deg={int(g.degrees.max())} cap={cap}",
        "backend": backend,
        "seconds": {
            "graph_build": round(t_build, 1),
            "triangle_counts_capped": round(t_tri, 1),
            "conductance_total": round(t_phi, 1),
            "rank_seeds": round(t_rank, 1),
            "covering_order_prep": round(t_order, 1),
            "covering_walk_k25000": round(t_cover, 2),
        },
        "tri_edges_per_sec": round(e / t_tri, 1),
        "seeding_edges_per_sec": round(e / (t_phi + t_rank), 1),
        "num_seeds": int(seeds.size),
        "num_covering_seeds": int(cover.size),
        "tri_mean": float(np.mean(tri)),
    }
    if t_dev is not None:
        rec["seconds"]["triangle_counts_device"] = round(t_dev, 1)
        rec["tri_device_edges_per_sec"] = round(e / t_dev, 1)
        rec["device_agrees_with_host"] = dev_agrees
    line = json.dumps(rec)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
