"""2D-partition comms gate (ISSUE 16): prove, on CPU fakes, that the
communication-avoiding 2D edge-block schedule actually avoids
communication — and changes nothing else.

Five check groups, the ISSUE 16 acceptance criteria verbatim:

  curve         modeled bytes/step vs p at fixed work on a SPARSE
                uniform toy (N=1024, avg degree ~4): 2D at (R,C)=(p,1)
                strictly
                below the 1D (p-1)/p full-F all-gather pricing at every
                p in {2,4,8}, and the 2D/1D ratio IMPROVES as p grows
                (the closure touched-fraction 1-exp(-deg/p) shrinks
                while 1D keeps shipping every row)
  reconcile     the static 2D comms model agrees (<=2% band) with the
                LIVE device buffers via the same remeasure path the 1D
                families gate on (obs.comms.measured_payloads, family
                "twod"), for both the (4,1) and the (2,2) grids
  identity      the 2D trajectory at C=1 is bit-identical to the 1D
                trainer (same llh scalar, array-equal F) — the closure
                gather is a layout change, not a math change; the (2,2)
                grid (partial-group psums + psum_scatter) stays inside
                the documented LLH band of 1D
  preflight     `cli preflight` flips the Friendster-scale verdict
                (N=65.6M, K=25000, sparse m=48, 64 v5e chips) from
                "does not fit" (exit 2, the 1D members all-gather
                binding, knobs naming --partition 2d) to "fits"
                (exit 0) under --partition 2d --replica-cols 8
  perf diff     the perf ledger refuses to baseline across partitions:
                an identical re-run baselines clean (exit 0), the same
                record restamped partition=2d finds NO baseline
                (exit 1) — a 2d run can never diff against a 1d run

    python scripts/comms2d_gate.py [COMMS2D_r20.json]

Exit 0 iff every check passes.
"""

import contextlib
import io
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else None

    import jax

    jax.config.update("jax_platforms", "cpu")
    from bigclam_tpu.utils.dist import request_cpu_devices

    request_cpu_devices(8)

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.graph.ingest import graph_from_edges
    from bigclam_tpu.obs import RunTelemetry, install, uninstall
    from bigclam_tpu.obs import ledger as L
    from bigclam_tpu.obs.report import load_events
    from bigclam_tpu.parallel import (
        ShardedBigClamModel,
        TwoDShardedBigClamModel,
        make_mesh,
        make_mesh_2d,
    )
    from bigclam_tpu.utils.profiling import StageProfile

    checks = {}
    detail = {}
    devs = jax.devices()

    # the curve needs a SPARSE graph with edges spread UNIFORMLY over
    # shard pairs: 2D undercuts 1D iff the closure cap < rows-per-
    # block, and the per-pair touched fraction n_blk*(1-exp(-e_pair/
    # n_blk)) only shrinks with p when e_pair ~ E/p^2. (A planted-
    # partition toy concentrates every edge on the diagonal pairs —
    # its touched fraction stays ~1-exp(-deg) at every p.) Uniform
    # Erdos-Renyi-style pairs at avg degree ~4: touched ~0.86 at p=2
    # down to ~0.39 at p=8.
    rng = np.random.default_rng(0)
    n_toy, m_toy = 1024, 2048
    pairs = rng.integers(0, n_toy, size=(4 * m_toy, 2))
    pairs = pairs[pairs[:, 0] != pairs[:, 1]]
    pairs = np.unique(np.sort(pairs, axis=1), axis=0)
    g = graph_from_edges(pairs[rng.permutation(len(pairs))[:m_toy]],
                         num_nodes=n_toy)
    K = 8
    F0 = np.abs(rng.standard_normal((g.num_nodes, K))).astype(np.float32)
    detail["toy"] = {
        "n": g.num_nodes,
        "edges": g.num_edges,
        "avg_degree": round(2 * g.num_edges / g.num_nodes, 2),
    }

    def cfg(**kw):
        d = dict(num_communities=K, max_iters=6, conv_tol=0.0,
                 health_every=2, seed=0)
        d.update(kw)
        return BigClamConfig(**d)

    # --- 1. bytes/step vs p: 2D strictly below 1D, ratio improving ----
    curve = {}
    ratios = []
    models_1d = {}
    models_2d = {}
    for p in (2, 4, 8):
        m1 = ShardedBigClamModel(g, cfg(), make_mesh((p, 1), devs[:p]))
        m2 = TwoDShardedBigClamModel(
            g, cfg(partition="2d", replica_cols=1),
            make_mesh_2d((p, 1), devs[:p]),
        )
        b1 = m1.comms.bytes_per_step()
        b2 = m2.comms.bytes_per_step()
        cap = int(m2._pad_stats["closure_cap"])
        n_blk = int(m2.n_pad // m2.p)
        curve[str(p)] = {
            "bytes_1d": round(b1, 1),
            "bytes_2d": round(b2, 1),
            "ratio": round(b2 / b1, 4),
            "closure_cap": cap,
            "rows_per_block": n_blk,
            "touched_fraction": round(cap / n_blk, 4),
        }
        checks[f"curve_p{p}_2d_below_1d"] = b2 < b1
        checks[f"curve_p{p}_cap_below_full_block"] = cap < n_blk
        ratios.append(b2 / b1)
        models_1d[p] = m1
        models_2d[p] = m2
    detail["curve"] = curve
    checks["curve_ratio_improves_with_p"] = (
        ratios[0] > ratios[1] > ratios[2]
    )

    # --- 2. modeled vs measured (<=2%), same remeasure path as 1D -----
    agreements = {}

    def agree(name, modeled, measured):
        rel = abs(measured - modeled) / max(modeled, 1e-9)
        agreements[name] = {
            "modeled_bytes_per_step": round(modeled, 1),
            "measured_bytes_per_step": round(measured, 1),
            "rel_diff": round(rel, 6),
        }
        checks[f"agree_{name}"] = rel <= 0.02

    st1 = models_1d[4].init_state(F0)
    agree("1d_dp4", models_1d[4].comms.bytes_per_step(),
          models_1d[4].comms_measured(st1).bytes_per_step())
    st2 = models_2d[4].init_state(F0)
    agree("2d_4x1", models_2d[4].comms.bytes_per_step(),
          models_2d[4].comms_measured(st2).bytes_per_step())
    m22 = TwoDShardedBigClamModel(
        g, cfg(partition="2d", replica_cols=2),
        make_mesh_2d((2, 2), devs[:4]),
    )
    st22 = m22.init_state(F0)
    agree("2d_2x2", m22.comms.bytes_per_step(),
          m22.comms_measured(st22).bytes_per_step())
    detail["agreements"] = agreements

    # --- 3. bit-identity at C=1, LLH band at (2,2) --------------------
    # the 1D dp=4 fit runs under telemetry so its finalized report
    # feeds the perf-ledger refusal check below
    work = tempfile.mkdtemp(prefix="comms2d_gate_")
    tdir = os.path.join(work, "fit1d")
    tel = install(RunTelemetry(tdir, entry="fit", quiet=True))
    try:
        with StageProfile().stage("fit"):
            r1 = models_1d[4].fit(F0.copy())
        tel.set_final({
            "llh": r1.llh, "iters": r1.num_iters, "n": g.num_nodes,
            "edges": g.num_edges, "k": K, "mesh": "4x1",
            "partition": "1d",
        })
        rep = tel.finalize()
    finally:
        uninstall(tel)

    r2 = models_2d[4].fit(F0.copy())
    F1, F2 = np.asarray(r1.F), np.asarray(r2.F)
    checks["identity_c1_llh_equal"] = r1.llh == r2.llh
    checks["identity_c1_F_array_equal"] = bool(np.array_equal(F1, F2))
    r22 = m22.fit(F0.copy())
    rel_llh = abs(r22.llh - r1.llh) / max(abs(r1.llh), 1.0)
    detail["identity"] = {
        "llh_1d": r1.llh,
        "llh_2d_4x1": r2.llh,
        "llh_2d_2x2": r22.llh,
        "rel_llh_2x2_vs_1d": rel_llh,
    }
    checks["llh_band_2x2"] = rel_llh < 5e-3

    # --- 4. preflight flips the Friendster-scale verdict --------------
    from bigclam_tpu.cli import main as cli_main

    fake = os.path.join(work, "edges.txt")
    with open(fake, "w") as f:
        f.write("0 1\n")
    base_args = [
        "preflight", "--graph", fake,
        "--nodes", "65608366", "--edges", "1806067135",
        "--k", "25000", "--representation", "sparse",
        "--sparse-m", "48", "--device-kind", "v5e",
        "--mesh", "64,1", "--json",
    ]

    def run_preflight(extra):
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli_main(base_args + extra)
        return rc, json.loads(buf.getvalue())

    rc_1d, p_1d = run_preflight([])
    rc_2d, p_2d = run_preflight(["--partition", "2d",
                                 "--replica-cols", "8"])
    checks["preflight_1d_does_not_fit"] = rc_1d == 2 and not p_1d["fits"]
    checks["preflight_1d_names_2d_knob"] = any(
        "--partition 2d" in k for k in p_1d["knobs"]
    )
    checks["preflight_2d_fits"] = rc_2d == 0 and p_2d["fits"]
    detail["preflight"] = {
        "binding_1d": p_1d.get("binding"),
        "hbm_1d": p_1d.get("hbm_modeled_bytes"),
        "hbm_2d": p_2d.get("hbm_modeled_bytes"),
        "rc_1d": rc_1d,
        "rc_2d": rc_2d,
    }

    # --- 5. perf ledger refuses to baseline across partitions ---------
    events = load_events(tdir) or []
    secs = [e["sec_per_iter"] for e in events
            if e.get("kind") == "step"
            and isinstance(e.get("sec_per_iter"), (int, float))]
    base_rec = L.build_record(rep, secs or [0.01] * 6)
    checks["record_carries_partition"] = base_rec.get("partition") == "1d"
    ledger_path = os.path.join(work, "ledger.jsonl")
    led = L.PerfLedger(ledger_path)
    led.append(base_rec)
    led.append(dict(base_rec, run="rerun", ts=base_rec["ts"] + 1))
    rc_same = cli_main(["perf", "diff", "--ledger", ledger_path])
    checks["perf_diff_same_partition_baselines"] = rc_same == 0
    # the SAME record restamped 2d: everything else about the run is
    # identical, yet it must find no 1d baseline to diff against
    led.append(dict(base_rec, run="as-2d", ts=base_rec["ts"] + 2,
                    partition="2d"))
    rc_cross = cli_main(["perf", "diff", "--ledger", ledger_path])
    checks["perf_diff_partition_refusal"] = rc_cross == 1
    detail["perf_diff"] = {"same_rc": rc_same, "cross_rc": rc_cross}

    ok = all(checks.values())
    artifact = {
        "gate": "comms2d_r20",
        "created_unix": round(time.time(), 1),
        "pass": ok,
        "checks": checks,
        "detail": detail,
        "device": str(jax.devices()[0]),
        "jax": jax.__version__,
        "note": (
            "2D closure-gather schedule strictly under the 1D full-F "
            "all-gather bytes/step at p in {2,4,8} on a degree-4 sparse "
            "toy, with the 2D/1D ratio improving as p grows; static 2D "
            "comms model within 2% of live buffers for (4,1) and (2,2); "
            "C=1 trajectory bit-identical to 1D and (2,2) inside the "
            "LLH band; cli preflight flips the Friendster-K25K-64xv5e "
            "verdict to FITS under --partition 2d --replica-cols 8; "
            "perf ledger refuses cross-partition baselines."
        ),
    }
    line = json.dumps(artifact, sort_keys=True)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    if not ok:
        bad = sorted(k for k, v in checks.items() if not v)
        print(f"FAILED checks: {bad}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
