"""Distributed-query-tracing gate (ISSUE 19): prove on CPU, multi-
process, that the trace plane tells the truth end to end:

  decompose         a traced drill through REAL `cli serve --fleet`
                    subprocesses + `cli route`: every qtrace exemplar's
                    hop breakdown sums to its measured end-to-end
                    latency within band (merge residual exact; per-hop
                    components account for the wire time)
  clean             the clean drill attributes NOTHING: every per-shard
                    hop mean stays under the fault threshold
  fault             a planted per-replica delay (BIGCLAM_QTRACE_FAULT)
                    is attributed to the RIGHT hop of the RIGHT shard —
                    a decode fault on shard 0 and an execute fault on
                    shard 1, simultaneously, each named by the per-shard
                    hop table (attribution is measured, not hardwired)
  offpath           trace-off answers are byte-identical to traced ones
                    and tracing costs <2% of routed wall time (best-of
                    alternating passes)
  freshness         generation_age_s + per-hop means land in the perf
                    ledger; a same-mix re-run baselines against the
                    first and `cli perf diff` VERDICTS them (ROADMAP 3a)
  fleetview         `cli report --fleet` / `cli watch --fleet` merge the
                    router's and every replica's telemetry dirs into one
                    fleet view with the per-hop decomposition

The whole gate is jax-free: the trace plane measures plumbing, not
model quality, so the fleet serves a random F (communities_of /
members_of never touch jax). Emits one JSON artifact (QTRACE_r23.json);
exit 0 iff every check passes.

    python scripts/qtrace_gate.py [out.json]
"""

import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N = 360
K = 12
SHARDS = 2
PASS_QUERIES = 1200         # per routed pass (overhead timing passes)
FAULT_DELAY_S = 0.03
FAULT_QUERIES = 40          # per shard, targeted communities_of
HOP_NAMES = ("transport", "decode", "queue", "batch_wait", "execute")


def _cli(*argv, env=None, check=True, timeout=600):
    p = subprocess.run(
        [sys.executable, "-m", "bigclam_tpu.cli", *argv],
        capture_output=True, text=True, env=env, timeout=timeout,
    )
    if check and p.returncode != 0:
        raise RuntimeError(
            f"cli {argv[0]} failed rc={p.returncode}\n"
            f"stdout: {p.stdout[-2000:]}\nstderr: {p.stderr[-2000:]}"
        )
    return p


def _last_json(text):
    return json.loads(text.strip().splitlines()[-1])


def _load_jsonl(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else None

    from bigclam_tpu.obs import ledger as L
    from bigclam_tpu.serve.snapshot import publish_fleet_snapshot

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    workdir = tempfile.mkdtemp(prefix="qtrace_gate_")
    checks = {}
    record = {"gate": "qtrace", "n": N, "k": K, "shards": SHARDS}
    procs = []

    def launch(shard, telemetry_dir=None, fault=None):
        penv = dict(env)
        if fault is not None:
            penv["BIGCLAM_QTRACE_FAULT"] = json.dumps(fault)
        argv = [sys.executable, "-m", "bigclam_tpu.cli", "serve",
                "--fleet", fleet_dir, "--fleet-shard", str(shard),
                "--listen", "127.0.0.1:0", "--latency-budget-ms", "1",
                "--quiet"]
        if telemetry_dir:
            argv += ["--telemetry-dir", telemetry_dir]
        p = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=penv,
        )
        procs.append(p)
        hello = json.loads(p.stdout.readline())
        return p, hello["listening"]

    def stop(endpoints, waitfor=()):
        _cli("route", "--fleet", fleet_dir, "--endpoints",
             ",".join(endpoints), "--stop", env=env)
        return [p.wait(timeout=30) for p in waitfor]

    try:
        # ---- one random-F publication (plumbing, not model quality) --
        rng = np.random.default_rng(7)
        F = rng.uniform(0.0, 1.5, size=(N, K))
        fleet_dir = os.path.join(workdir, "fleet")
        ranges = [(s * N // SHARDS, (s + 1) * N // SHARDS)
                  for s in range(SHARDS)]
        publish_fleet_snapshot(fleet_dir, ranges, F=F, num_edges=4 * N)

        # Zipf-ish read mix: communities_of over all nodes + members_of
        # scatter-gathers (multi-hop traces exercise the merge residual)
        qrng = np.random.default_rng(11)
        queries = (
            [{"family": "communities_of", "u": int(u)}
             for u in qrng.integers(0, N, int(PASS_QUERIES * 0.7))]
            + [{"family": "members_of", "c": int(c)}
               for c in qrng.integers(0, K, PASS_QUERIES
                                      - int(PASS_QUERIES * 0.7))]
        )
        qrng.shuffle(queries)
        qfile = os.path.join(workdir, "q.jsonl")
        with open(qfile, "w") as f:
            for q in queries:
                f.write(json.dumps(q) + "\n")

        # ---- fleet root: the router's and each replica's telemetry
        # dirs as SIBLING subdirectories (the report/watch --fleet
        # convention)
        fleetroot = os.path.join(workdir, "telem")
        clean_procs, eps = [], []
        for s in range(SHARDS):
            p, ep = launch(
                s, telemetry_dir=os.path.join(fleetroot, f"rep{s}"))
            clean_procs.append(p)
            eps.append(ep)
        endpoints = ",".join(eps)
        record["endpoints"] = eps

        # ---- offpath: byte parity + overhead, alternating best-of ----
        # sequential routing for the timing: at 16 concurrent workers a
        # saturated 2-replica CPU fleet's pass wall varies ~20% between
        # IDENTICAL passes (GIL + scheduler), swamping a 2% pin. One
        # worker serializes the per-query path, and the MEDIAN latency
        # (not the pass wall, which one straggler can own) is the
        # per-query tracing cost the contract pins.
        p50s = {"off": [], "on": []}
        answers = {}
        for i in range(4):
            for mode in ("off", "on"):
                argv = ["route", "--fleet", fleet_dir,
                        "--endpoints", endpoints, "--queries", qfile,
                        "--max-workers", "1", "--quiet"]
                if mode == "on":
                    argv += ["--telemetry-dir",
                             os.path.join(workdir, f"t_on_{i}")]
                if i == 0:
                    answers[mode] = os.path.join(
                        workdir, f"ans_{mode}.jsonl")
                    argv += ["--results", answers[mode]]
                st = _last_json(_cli(*argv, env=env).stdout)
                if st["serve_errors"]:
                    raise RuntimeError(f"{mode} pass errored: {st}")
                p50s[mode].append(st["serve_p50_s"])
        best_off, best_on = min(p50s["off"]), min(p50s["on"])
        record["offpath"] = {
            "p50_off_us": [round(v * 1e6, 1) for v in p50s["off"]],
            "p50_on_us": [round(v * 1e6, 1) for v in p50s["on"]],
            "overhead": round(best_on / best_off - 1.0, 4),
        }
        checks["offpath_answers_byte_identical"] = (
            open(answers["off"]).read() == open(answers["on"]).read()
        )
        # <2% of the best-of-4 median per-query latency, with a 20 us
        # floor so a ~7 us hop block is not failed by timer granularity
        checks["offpath_overhead_under_2pct"] = (
            best_on <= best_off * 1.02 + 20e-6
        )

        # ---- the traced drill (router telemetry inside the root) -----
        ledger_path = os.path.join(workdir, "ledger.jsonl")
        router_dir = os.path.join(fleetroot, "router")
        drill = _last_json(_cli(
            "route", "--fleet", fleet_dir, "--endpoints", endpoints,
            "--queries", qfile, "--repeat", "2",
            "--telemetry-dir", router_dir, "--perf-ledger", ledger_path,
            "--quiet", env=env,
        ).stdout)
        shard_hops = {
            s: st.get("hops") or {}
            for s, st in (drill.get("serve_shard_stats") or {}).items()
        }
        record["drill"] = {
            "queries": drill["serve_queries"],
            "traced": drill["traced_queries"],
            "p99_ms": round(drill["serve_p99_s"] * 1e3, 3),
            "hop_means_s": {
                h: drill.get(f"serve_hop_{h}_s")
                for h in HOP_NAMES + ("merge",)
            },
            "shard_hops": shard_hops,
        }
        checks["drill_every_query_traced"] = (
            drill["traced_queries"] == drill["serve_queries"]
            == 2 * len(queries) and drill["serve_errors"] == 0
        )
        checks["drill_hop_means_recorded"] = all(
            isinstance(drill.get(f"serve_hop_{h}_s"), float)
            for h in HOP_NAMES + ("merge",)
        )

        # decomposition: every qtrace exemplar reconciles with its
        # measured end-to-end latency. The merge residual closes the
        # trace level EXACTLY (rounding only); the per-hop components
        # must account for each wire interval within band.
        events = _load_jsonl(os.path.join(router_dir, "events.jsonl"))
        exemplars = [e for e in events if e["kind"] == "qtrace"]
        freshness = [e for e in events if e["kind"] == "freshness"]
        record["exemplars"] = len(exemplars)
        trace_ok = hop_ok = 0
        for rec in exemplars:
            acct = sum(h["wire_s"] for h in rec["hops"]) + rec["merge_s"]
            if abs(rec["total_s"] - acct) < 1e-4:
                trace_ok += 1
            # the residual gap is future-wakeup / dispatch scheduling
            # inside the replica — real time, attributable to no single
            # hop. Exemplars are the WORST traces of the window (that
            # wakeup latency is often why they are slow), hence the
            # wider band than the trace-level identity above.
            if all(
                -1e-4 <= h["wire_s"] - (
                    h.get("transport_s", 0.0) + h["decode_s"]
                    + h["queue_s"] + h["batch_wait_s"] + h["execute_s"]
                ) <= max(0.35 * h["wire_s"], 0.005)
                for h in rec["hops"]
            ):
                hop_ok += 1
        record["decompose"] = {"traces": len(exemplars),
                               "trace_ok": trace_ok, "hop_ok": hop_ok}
        checks["decompose_exemplars_emitted"] = len(exemplars) >= 5
        checks["decompose_totals_reconcile"] = (
            trace_ok == len(exemplars) > 0
        )
        # >=80%: exemplars are the worst traces of a SATURATED CPU
        # drill — the single slowest can owe most of its wire time to a
        # scheduler wakeup no hop can claim. The trace-level identity
        # above still holds for every one of them.
        checks["decompose_hops_account_for_wire"] = (
            hop_ok >= max(1, int(0.8 * len(exemplars)))
        )
        checks["freshness_events_emitted"] = (
            len(freshness) >= 1
            and all(f["generation_age_s"] >= 0.0 for f in freshness)
        )

        # clean attribution: no hop mean anywhere near the fault bar
        checks["clean_run_attributes_nothing"] = all(
            v < FAULT_DELAY_S / 2
            for hops in shard_hops.values()
            for v in hops.values()
        )

        # ---- ledger re-run + `cli perf diff` verdicts ----------------
        rerun = _last_json(_cli(
            "route", "--fleet", fleet_dir, "--endpoints", endpoints,
            "--queries", qfile, "--repeat", "2",
            "--telemetry-dir", os.path.join(workdir, "telem2"),
            "--perf-ledger", ledger_path, "--quiet", env=env,
        ).stdout)
        checks["ledger_rerun_clean"] = rerun["serve_errors"] == 0
        diff_p = _cli("perf", "diff", "--ledger", ledger_path,
                      "--tolerance", "5.0", env=env, check=False)
        record["perf_diff_rc"] = diff_p.returncode
        checks["perf_diff_passes"] = diff_p.returncode == 0
        route_recs = [r for r in L.PerfLedger(ledger_path).load()
                      if r.get("entry") == "route"]
        if len(route_recs) == 2:
            d = L.diff_records(route_recs[0], route_recs[1],
                               tolerance=5.0)
            verdicted = {
                c["metric"] for c in d["checks"]
                if c.get("verdicted") and not c.get("skipped")
            }
            record["verdicted_metrics"] = sorted(verdicted)
            checks["freshness_verdicted_in_ledger"] = (
                "generation_age_s" in verdicted
            )
            checks["hop_verdicted_in_ledger"] = (
                "serve_hop_execute_s" in verdicted
            )
        else:
            checks["freshness_verdicted_in_ledger"] = False
            checks["hop_verdicted_in_ledger"] = False

        # ---- teardown the clean fleet, then the merged fleet view ----
        codes = stop(eps, waitfor=clean_procs)
        checks["teardown_clean_exits"] = all(c == 0 for c in codes)
        rep = _cli("report", "--fleet", fleetroot, env=env).stdout
        checks["report_fleet_renders"] = (
            "3 member dir(s)" in rep and "per-hop mean" in rep
            and "replica rep0" in rep and "replica rep1" in rep
        )
        fobj = _last_json(_cli(
            "report", "--fleet", fleetroot, "--json", env=env).stdout)
        checks["report_fleet_json_merges"] = (
            fobj["router"]["traced_queries"] == drill["traced_queries"]
            and sorted(fobj["replicas"]) == ["0", "1"]
        )
        watch = _cli("watch", "--fleet", fleetroot, "--once",
                     env=env).stdout
        checks["watch_fleet_renders"] = (
            "3 member(s)" in watch and "slow traces" in watch
        )

        # ---- planted faults: decode on shard 0, execute on shard 1 ---
        fault_procs, feps = [], []
        for s, hop in ((0, "decode"), (1, "execute")):
            p, ep = launch(
                s, fault={"hop": hop, "delay_s": FAULT_DELAY_S})
            fault_procs.append(p)
            feps.append(ep)
        fq = os.path.join(workdir, "fq.jsonl")
        with open(fq, "w") as f:
            for s in range(SHARDS):
                lo, hi = ranges[s]
                for u in qrng.integers(lo, hi, FAULT_QUERIES):
                    f.write(json.dumps(
                        {"family": "communities_of", "u": int(u)}) + "\n")
        # sequential routing: no batch-mates, so the planted delay
        # cannot cascade into batch_wait/queue congestion — the hop
        # table isolates exactly where the time went
        fstats = _last_json(_cli(
            "route", "--fleet", fleet_dir, "--endpoints", ",".join(feps),
            "--queries", fq, "--max-workers", "1",
            "--telemetry-dir", os.path.join(workdir, "telem_fault"),
            "--quiet", env=env,
        ).stdout)
        fhops = {s: st.get("hops") or {}
                 for s, st in fstats["serve_shard_stats"].items()}
        record["fault"] = {"delay_s": FAULT_DELAY_S, "shard_hops": fhops}
        for s, hop in (("0", "decode"), ("1", "execute")):
            hops = fhops.get(s) or {}
            checks[f"fault_shard{s}_attributed_to_{hop}"] = (
                bool(hops)
                and max(hops, key=hops.get) == hop
                and hops[hop] >= FAULT_DELAY_S / 2
                and all(v < FAULT_DELAY_S / 2
                        for k, v in hops.items() if k != hop)
            )
        stop(feps, waitfor=fault_procs)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    # ---- verdict ----------------------------------------------------
    record["checks"] = checks
    record["pass"] = all(checks.values())
    line = json.dumps(record)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    return 0 if record["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
