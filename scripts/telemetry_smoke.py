"""Telemetry smoke gate (ISSUE 4 satellite; span tracing added in ISSUE
6): run a tiny CPU fit with the full telemetry stack on — event log,
watermarks, compile counters, a sub-second stall heartbeat, metrics sink,
span tracing — validate EVERY event line against the schema
(bigclam_tpu.obs.schema), check the run report's structure, check that
the per-span breakdown's TOP-LEVEL spans cover >= 95% of the run's wall
time (the ISSUE 6 acceptance: no unattributed time on the smoke), and
emit one JSON artifact line.

    python scripts/telemetry_smoke.py [out.json]

Exit 0 iff every check passes. The committed artifact (TELEM_SMOKE_r10.json)
is the proof the producer and the schema agree at the commit that shipped
them; the same validation runs in tier-1 (tests/test_telemetry.py +
tests/test_trace.py), so drift between them fails CI, not a Friendster
run.
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else None

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.models import BigClamModel
    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.obs import (
        RunTelemetry,
        install,
        uninstall,
        validate_events_file,
    )
    from bigclam_tpu.obs.report import render
    from bigclam_tpu.obs.telemetry import EVENTS_NAME
    from bigclam_tpu.utils.metrics import MetricsLogger

    g, _ = sample_planted_graph(240, 4, p_in=0.3, rng=np.random.default_rng(0))
    cfg = BigClamConfig(
        num_communities=4, dtype="float64", max_iters=8, conv_tol=0.0
    )
    F0 = np.random.default_rng(1).uniform(0.1, 1.0, size=(g.num_nodes, 4))

    tdir = tempfile.mkdtemp(prefix="telem_smoke_")
    checks = {}
    tel = install(
        RunTelemetry(tdir, entry="smoke", heartbeat_s=60.0, quiet=True)
    )
    try:
        from bigclam_tpu.utils.profiling import StageProfile

        prof = StageProfile()     # stage-boundary events + watermarks,
        with prof.stage("model_build"):    # the entry-point pattern
            model = BigClamModel(g, cfg)
        with prof.stage("fit"), MetricsLogger(None, echo=False) as ml:
            res = model.fit(
                F0,
                callback=ml.step_callback(
                    g.num_directed_edges, num_nodes=g.num_nodes
                ),
            )
        tel.set_final({"llh": res.llh, "iters": res.num_iters})
        refit_base = tel.compile_count()
        with prof.stage("refit"):       # spanned: coverage must hold
            model.fit(F0)               # re-fit: count must stay flat
        checks["compile_count_flat_on_refit"] = (
            tel.compile_count() == refit_base
        )
        rep = tel.finalize()
    finally:
        uninstall(tel)

    from bigclam_tpu.obs.report import span_coverage

    n_events, errors = validate_events_file(os.path.join(tdir, EVENTS_NAME))
    checks["all_events_schema_valid"] = not errors
    checks["has_step_events"] = rep["events"].get("step", 0) >= cfg.max_iters
    checks["has_stage_seconds"] = bool(rep["stages"]["seconds"])
    checks["has_compile_count"] = rep["compiles"]["count"] > 0
    checks["has_device_watermarks"] = bool(rep["memory"]["device_peak"])
    checks["report_renders"] = render(tdir)[1] == 0
    # --- ISSUE 6: span tracing rides the same smoke ---
    spans = rep["spans"]["seconds"]
    coverage = span_coverage(rep)
    # every stage has a same-named span, and the fit loop's phases
    # aggregated under the "fit" stage span
    checks["every_stage_has_a_span"] = all(
        s in spans for s in rep["stages"]["seconds"]
    )
    checks["fit_loop_phase_spans_present"] = all(
        f"fit/fit_loop/{p}" in spans for p in ("dispatch", "sync")
    )
    checks["span_events_schema_valid"] = rep["events"].get("span", 0) > 0
    checks["no_span_orphans"] = rep["spans"]["orphans"] == 0
    # the acceptance bound: top-level spans sum to within 5% of wall
    checks["span_coverage_ge_95pct"] = (
        coverage is not None and 0.95 <= coverage <= 1.05
    )

    record = {
        "gate": "telemetry-smoke",
        "config": f"planted AGM N={g.num_nodes} K=4 "
                  f"2E={g.num_directed_edges}, max_iters={cfg.max_iters}",
        "n_events": n_events,
        "event_kinds": rep["events"],
        "compiles": rep["compiles"]["count"],
        "schema_errors": errors[:10],
        "span_seconds": spans,
        "span_coverage": round(coverage, 4) if coverage else None,
        "checks": checks,
        "device": str(jax.devices()[0]),
        "jax": jax.__version__,
        "pass": all(checks.values()),
    }
    line = json.dumps(record)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    return 0 if record["pass"] else 1


if __name__ == "__main__":
    main_rc = main()
    sys.exit(main_rc)
