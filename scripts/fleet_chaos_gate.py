"""Self-healing-fleet chaos gate (ISSUE 20): drill a REAL multi-process
2 shards x 2 replicas fleet — supervisor-owned `cli serve --fleet`
subprocesses behind a `cli route --daemon` router — through the failure
ladder, and prove the client never sees any of it:

  parity            fault-off: a Zipf mix (45% members_of / 45%
                    communities_of / 10% suggest_for) streamed through
                    the router daemon is bit-identical to a
                    single-process `cli serve` on the same F, with zero
                    retries/hedges/deadline misses (byte-identical to
                    the PR 18 fleet when nothing is failing)
  kill -9           one replica SIGKILLed under a live stream: zero
                    client errors (the in-flight queries surface as
                    RETRIED answers), the supervisor restarts the slot,
                    and the rejoined replica serves the NEWEST
                    generation (a mid-drill publication flips the whole
                    fleet, restarted member included)
  crash loop        `fleet add-replica` lands on a slot the fault plan
                    kills at replica.start on EVERY spawn: after
                    quarantine_after consecutive failures the slot is
                    parked "quarantined" while the fleet keeps
                    answering (degraded, never down)
  drain + add       `fleet add-replica` + `fleet drain` reshape the
                    fleet MID-STREAM with zero dropped queries; planted
                    torn-frame + stall wire faults on the new member
                    are recovered by the router's bounded reader +
                    failover and attributed as retried trace hops
  hedge             a separate 1x2 fleet with one slowed replica: the
                    duplicate fired after --hedge-delay-s wins
                    (hedged > 0, hedge_wins > 0, zero errors)
  ledger/report     the daemon + supervisor runs land
                    router_retries/hedged_rate/deadline_exceeded_rate/
                    replica_restarts in the perf ledger; `cli report
                    --fleet` renders the supervisor roster and the
                    self-healing counters

Emits one JSON artifact (FLEETCHAOS_r24.json); exit 0 iff every check
passes.

    python scripts/fleet_chaos_gate.py [out.json]
"""

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N = 240
K = 8
P_IN = 0.7
PARITY_QUERIES = 900
STREAM_QUERIES = 2400
ZIPF_A = 1.3


def _zipf_rank(rng, n, size):
    out = rng.zipf(ZIPF_A, size=size * 2) - 1
    out = out[out < n]
    while out.size < size:
        more = rng.zipf(ZIPF_A, size=size) - 1
        out = np.concatenate([out, more[more < n]])
    return out[:size]


def _cli(*argv, env=None, check=True, timeout=600):
    p = subprocess.run(
        [sys.executable, "-m", "bigclam_tpu.cli", *argv],
        capture_output=True, text=True, env=env, timeout=timeout,
    )
    if check and p.returncode != 0:
        raise RuntimeError(
            f"cli {argv[0]} failed rc={p.returncode}\n"
            f"stdout: {p.stdout[-2000:]}\nstderr: {p.stderr[-2000:]}"
        )
    return p


def _last_json(text):
    return json.loads(text.strip().splitlines()[-1])


def _load_jsonl(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def _wire(endpoint, q, timeout=120.0):
    host, port = endpoint.rsplit(":", 1)
    with socket.create_connection((host, int(port)),
                                  timeout=timeout) as sock:
        sock.settimeout(timeout)
        sock.sendall((json.dumps(q) + "\n").encode())
        return json.loads(sock.makefile("rb").readline())


def _wait_for(pred, timeout=60.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


class _Stream:
    """Threaded live query stream against the router daemon: each
    thread owns one connection, requests are strictly request/response
    per connection, every answer is classified ok/error."""

    def __init__(self, routing, queries, threads=8, pace_s=0.0):
        self.routing = routing
        self.queries = list(queries)
        self.pace_s = pace_s
        self.idx = 0
        self.ok = 0
        self.errors = []
        self.lock = threading.Lock()
        self._threads = [
            threading.Thread(target=self._run, daemon=True)
            for _ in range(threads)
        ]

    def _next(self):
        with self.lock:
            if self.idx >= len(self.queries):
                return None
            q = self.queries[self.idx]
            self.idx += 1
            return q

    def _run(self):
        host, port = self.routing.rsplit(":", 1)
        try:
            sock = socket.create_connection((host, int(port)),
                                            timeout=120.0)
            sock.settimeout(120.0)
            rfile = sock.makefile("rb")
        except OSError as e:
            with self.lock:
                self.errors.append({"error": f"connect: {e}"})
            return
        while True:
            q = self._next()
            if q is None:
                break
            try:
                sock.sendall((json.dumps(q) + "\n").encode())
                ans = json.loads(rfile.readline())
            except (OSError, ValueError) as e:
                ans = {"error": f"{type(e).__name__}: {e}"}
            with self.lock:
                if isinstance(ans, dict) and "error" in ans:
                    self.errors.append({"q": q, "ans": ans})
                else:
                    self.ok += 1
            if self.pace_s:
                time.sleep(self.pace_s)
        rfile.close()
        sock.close()

    def start(self):
        for t in self._threads:
            t.start()
        return self

    def join(self, timeout=300.0):
        deadline = time.monotonic() + timeout
        for t in self._threads:
            t.join(max(deadline - time.monotonic(), 0.1))
        return self


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else None

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.graph.store import compile_graph_cache
    from bigclam_tpu.models import BigClamModel
    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.obs import ledger as L
    from bigclam_tpu.serve.snapshot import (
        publish_fleet_snapshot,
        publish_snapshot,
    )

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    env.pop("BIGCLAM_FAULTS", None)
    workdir = tempfile.mkdtemp(prefix="fleet_chaos_gate_")
    telem = os.path.join(workdir, "telem")
    ledger_path = os.path.join(workdir, "ledger.jsonl")
    members = os.path.join(workdir, "members.json")
    checks = {}
    record = {"gate": "fleet_chaos", "n": N, "k": K, "p_in": P_IN}
    procs = []

    try:
        # ---- one fit, the publications, the graph cache --------------
        rng = np.random.default_rng(7)
        g, _ = sample_planted_graph(N, K, p_in=P_IN, rng=rng)
        etxt = os.path.join(workdir, "g.txt")
        with open(etxt, "w") as f:
            for u in range(g.num_nodes):
                for j in range(g.indptr[u], g.indptr[u + 1]):
                    v = int(g.indices[j])
                    if u < v:
                        f.write(f"{g.raw_ids[u]} {g.raw_ids[v]}\n")
        cache = os.path.join(workdir, "g.cache")
        store = compile_graph_cache(etxt, cache, num_shards=4)

        cfg = BigClamConfig(num_communities=K, max_iters=400)
        model = BigClamModel(g, cfg)
        res = model.fit(model.random_init())
        record["fit_llh"] = res.llh

        single_dir = os.path.join(workdir, "single")
        publish_snapshot(
            single_dir, step=1, F=res.F, raw_ids=g.raw_ids,
            num_edges=g.num_edges, cfg=cfg, meta={"llh": res.llh},
        )
        fleet_dir = os.path.join(workdir, "fleet")
        ranges = store.host_ranges(2)
        gen1, _ = publish_fleet_snapshot(
            fleet_dir, ranges, F=res.F, raw_ids=g.raw_ids,
            num_edges=g.num_edges, cfg=cfg, meta={"llh": res.llh},
        )
        record["gen1"] = gen1

        # ---- the supervised fleet: 2x2 under `cli fleet up` ----------
        # the fault plan rides the supervisor env so every replica
        # inherits it; the specs match members that only exist AFTER
        # the elastic drills create them (s0r2: crash loop at start;
        # s1r2: torn frame + stall on its answer wire)
        sup_env = dict(env)
        sup_env["BIGCLAM_FAULTS"] = json.dumps({"faults": [
            {"kind": "kill", "site": "replica.start",
             "member": "s0r2", "at": 0},
            {"kind": "torn_frame", "site": "replica.answer_write",
             "member": "s1r2", "at": 5},
            {"kind": "stall", "site": "replica.answer_write",
             "member": "s1r2", "seconds": 5.0, "at": 12},
        ]})
        fleet_up = subprocess.Popen(
            [sys.executable, "-m", "bigclam_tpu.cli", "fleet", "up",
             "--fleet", fleet_dir, "--shards", "2", "--replicas", "2",
             "--members", members, "--graph", cache,
             "--replica-args",
             "--latency-budget-ms 1 --max-queue-depth 4096",
             "--watch-snapshots", "0.2",
             "--restart-base-s", "0.05", "--restart-max-s", "0.3",
             "--stable-s", "0.5", "--quarantine-after", "2",
             "--drain-grace-s", "0.4", "--up-timeout-s", "120",
             "--telemetry-dir", os.path.join(telem, "fleet"),
             "--perf-ledger", ledger_path, "--quiet"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=sup_env,
        )
        procs.append(fleet_up)
        hello = json.loads(fleet_up.stdout.readline())
        control = hello["control"]
        checks["fleet_up_all_up"] = (
            hello["all_up"] is True
            and sorted(hello["fleet_members"])
            == ["s0r0", "s0r1", "s1r0", "s1r1"]
        )

        # warm the jax suggest path on every replica: the fold-in jit
        # is compiled per padded (batch, degree) bucket, so hit every
        # bucket a real query can land in on every replica — the
        # router's 2s request timeout must never race a cold compile
        # in the fault-off parity pass
        with open(members) as f:
            roster = json.load(f)["members"]

        def _pow2(x):
            return 1 << max(int(x) - 1, 0).bit_length()

        degs = np.diff(g.indptr)
        buckets = sorted({max(_pow2(int(d)), 1) for d in degs})
        record["warm_buckets"] = buckets

        def warm(ep):
            for d in buckets:
                _wire(ep, {"family": "suggest_rows",
                           "neighbor_rows": [[0.1] * K] * d,
                           "own_row": None}, timeout=300.0)

        def warm_all(eps):
            ts = [threading.Thread(target=warm, args=(ep,))
                  for ep in eps]
            for t in ts:
                t.start()
            for t in ts:
                t.join(300.0)

        warm_all([m["endpoint"] for m in roster])

        # ---- the router daemon over the watched membership file ------
        daemon = subprocess.Popen(
            [sys.executable, "-m", "bigclam_tpu.cli", "route",
             "--fleet", fleet_dir, "--members", members, "--daemon",
             "--listen", "127.0.0.1:0", "--wait-fleet-s", "60",
             "--request-timeout-s", "2", "--deadline-s", "30",
             "--retry-rounds", "3", "--health-interval-s", "0.15",
             "--telemetry-dir", os.path.join(telem, "router"),
             "--perf-ledger", ledger_path, "--quiet"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        procs.append(daemon)
        routing = json.loads(daemon.stdout.readline())["routing"]
        record["routing"] = routing

        def mix(rng_q, size):
            n_m = int(size * 0.45)
            n_c = int(size * 0.45)
            n_s = size - n_m - n_c
            qs = (
                [{"family": "members_of", "c": int(r)}
                 for r in _zipf_rank(rng_q, K, n_m)]
                + [{"family": "communities_of",
                    "u": int(g.raw_ids[int(r)])}
                   for r in _zipf_rank(rng_q, N, n_c)]
                + [{"family": "suggest_for",
                    "u": int(g.raw_ids[int(r)])}
                   for r in _zipf_rank(rng_q, N, n_s)]
            )
            rng_q.shuffle(qs)
            return qs

        qrng = np.random.default_rng(11)
        parity_q = mix(qrng, PARITY_QUERIES)

        # ---- phase 1: fault-off parity vs single-process serve -------
        answers = []
        host, port = routing.rsplit(":", 1)
        with socket.create_connection((host, int(port)),
                                      timeout=120.0) as sock:
            sock.settimeout(120.0)
            rfile = sock.makefile("rb")
            for q in parity_q:
                sock.sendall((json.dumps(q) + "\n").encode())
                answers.append(json.loads(rfile.readline()))
        qfile = os.path.join(workdir, "parity_q.jsonl")
        with open(qfile, "w") as f:
            for q in parity_q:
                f.write(json.dumps(q) + "\n")
        single_answers = os.path.join(workdir, "single_answers.jsonl")
        _cli(
            "serve", "--snapshots", single_dir, "--graph", cache,
            "--queries", qfile, "--results", single_answers, "--quiet",
            env=env,
        )
        want = [
            {k: v for k, v in r.items() if k != "cached"}
            for r in _load_jsonl(single_answers)
        ]
        mism = sum(1 for x, y in zip(answers, want) if x != y)
        record["parity"] = {"compared": len(answers),
                           "mismatches": mism}
        checks["parity_bit_identical_via_daemon"] = (
            len(answers) == len(want) == PARITY_QUERIES and mism == 0
        )
        st0 = _wire(routing, {"family": "status"})
        checks["parity_fault_off_clean"] = (
            st0["serve_errors"] == 0
            and st0["router_retries"] == 0
            and st0["hedged"] == 0
            and st0["deadline_exceeded"] == 0
        )

        # ---- phase 2: kill -9 one replica under a live stream --------
        stream = _Stream(routing, mix(qrng, STREAM_QUERIES),
                         threads=8, pace_s=0.002).start()
        assert _wait_for(lambda: stream.idx >= 400, timeout=60.0)
        with open(members) as f:
            victim = next(m for m in json.load(f)["members"]
                          if m["id"] == "s0r0")
        os.kill(victim["pid"], signal.SIGKILL)
        stream.join()

        def fleet_status():
            return _wire(control, {"op": "status"})

        def healed():
            st = fleet_status()
            by_id = {m["id"]: m for m in st["members"]}
            return (st["replica_restarts"] >= 1
                    and by_id["s0r0"]["state"] == "up"
                    and by_id["s0r0"]["pid"] != victim["pid"])

        checks["kill_restarted_by_supervisor"] = _wait_for(
            healed, timeout=60.0
        )
        st1 = _wire(routing, {"family": "status"})
        record["kill"] = {
            "streamed": stream.ok,
            "client_errors": stream.errors[:5],
            "router_retries": st1["router_retries"],
            "transport_failovers": st1["transport_failovers"],
        }
        checks["kill_zero_client_errors"] = (
            not stream.errors and stream.ok == STREAM_QUERIES
        )
        checks["kill_surfaced_as_retried"] = st1["router_retries"] >= 1

        # rejoin at the NEWEST generation: a mid-drill publication can
        # only flip the serving generation if EVERY healthy replica —
        # the restarted one included — loads it
        gen2, _ = publish_fleet_snapshot(
            fleet_dir, ranges, F=res.F, raw_ids=g.raw_ids,
            num_edges=g.num_edges, cfg=cfg, meta={"llh": res.llh},
        )
        record["gen2"] = gen2
        checks["kill_rejoined_at_newest_generation"] = _wait_for(
            lambda: _wire(routing, {"family": "status"})
            ["serving_generation"] == gen2,
            timeout=60.0,
        )
        # gen2 engines are cold (the fold-in jit is per generation):
        # re-warm every CURRENT endpoint — including the restarted
        # s0r0's new port — so later streams only see the faults we
        # planted, not compile stalls
        with open(members) as f:
            warm_all([m["endpoint"]
                      for m in json.load(f)["members"]
                      if m["state"] == "up"])

        # ---- phase 3: crash loop -> quarantine, fleet still serving --
        stream = _Stream(routing, mix(qrng, STREAM_QUERIES),
                         threads=8, pace_s=0.004).start()
        add = _wire(control, {"op": "add_replica", "shard": 0})
        checks["quarantine_slot_added"] = (
            add["ok"] and add["member"]["id"] == "s0r2"
        )
        checks["quarantine_parked_crash_loop"] = _wait_for(
            lambda: fleet_status()["quarantined"] >= 1, timeout=60.0
        )
        st = fleet_status()
        by_id = {m["id"]: m for m in st["members"]}
        checks["quarantine_state_published"] = (
            by_id["s0r2"]["state"] == "quarantined"
        )
        stream.join()
        record["quarantine"] = {
            "streamed": stream.ok,
            "client_errors": stream.errors[:5],
            "replica_restarts": st["replica_restarts"],
        }
        checks["quarantine_fleet_still_serving"] = (
            not stream.errors and stream.ok == STREAM_QUERIES
        )

        # ---- phase 4: drain + add mid-stream, planted wire faults ----
        st_before = _wire(routing, {"family": "status"})
        stream = _Stream(routing, mix(qrng, STREAM_QUERIES),
                         threads=8, pace_s=0.012).start()
        assert _wait_for(lambda: stream.idx >= 100, timeout=60.0)
        add = _wire(control, {"op": "add_replica", "shard": 1})
        checks["elastic_add_mid_stream"] = (
            add["ok"] and add["member"]["id"] == "s1r2"
        )
        assert _wait_for(
            lambda: {m["id"]: m["state"]
                     for m in fleet_status()["members"]}
            .get("s1r2") == "up",
            timeout=60.0,
        )
        drain = _wire(control, {"op": "drain", "member": "s1r0"},
                      timeout=120.0)
        checks["elastic_drain_mid_stream"] = drain["ok"] is True
        stream.join()
        st_after = _wire(routing, {"family": "status"})
        by_id = {m["id"]: m for m in fleet_status()["members"]}
        record["drain_add"] = {
            "streamed": stream.ok,
            "client_errors": stream.errors[:5],
            "retried_delta": (st_after["router_retries"]
                              - st_before["router_retries"]),
        }
        checks["elastic_zero_dropped_queries"] = (
            not stream.errors and stream.ok == STREAM_QUERIES
        )
        checks["drained_member_stopped"] = (
            by_id["s1r0"]["state"] == "stopped"
        )
        checks["planted_wire_faults_recovered"] = (
            st_after["router_retries"] > st_before["router_retries"]
        )

        # ---- phase 5: hedge micro-drill (separate 1x2 fleet) ---------
        fleet1_dir = os.path.join(workdir, "fleet1")
        publish_fleet_snapshot(
            fleet1_dir, [(0, N)], F=res.F, raw_ids=g.raw_ids,
            num_edges=g.num_edges, cfg=cfg,
        )
        hedge_eps = []
        for i in range(2):
            renv = dict(env)
            if i == 0:
                renv["BIGCLAM_QTRACE_FAULT"] = json.dumps(
                    {"hop": "execute", "delay_s": 0.12}
                )
            p = subprocess.Popen(
                [sys.executable, "-m", "bigclam_tpu.cli", "serve",
                 "--fleet", fleet1_dir, "--fleet-shard", "0",
                 "--listen", "127.0.0.1:0", "--latency-budget-ms", "1",
                 "--quiet"],
                stdout=subprocess.PIPE, text=True, env=renv,
            )
            procs.append(p)
            hedge_eps.append(json.loads(p.stdout.readline())["listening"])
        hedge_q = os.path.join(workdir, "hedge_q.jsonl")
        with open(hedge_q, "w") as f:
            for r in _zipf_rank(qrng, N, 300):
                f.write(json.dumps(
                    {"family": "communities_of",
                     "u": int(g.raw_ids[int(r)])}) + "\n")
        hedge = _last_json(_cli(
            "route", "--fleet", fleet1_dir,
            "--endpoints", ",".join(hedge_eps),
            "--queries", hedge_q, "--hedge", "--hedge-delay-s", "0.02",
            "--quiet", env=env,
        ).stdout)
        record["hedge"] = {
            "hedged": hedge["hedged"],
            "hedge_wins": hedge["hedge_wins"],
            "hedged_rate": hedge["hedged_rate"],
            "p99_ms": round(hedge["serve_p99_s"] * 1e3, 3),
        }
        checks["hedge_fired_and_won"] = (
            hedge["hedged"] > 0 and hedge["hedge_wins"] > 0
            and hedge["serve_errors"] == 0
        )
        _cli("route", "--fleet", fleet1_dir,
             "--endpoints", ",".join(hedge_eps), "--stop", env=env)

        # ---- teardown: daemon stop, fleet down -----------------------
        assert _wire(routing, {"family": "stop"})["ok"] is True
        d_out, d_err = daemon.communicate(timeout=60)
        checks["daemon_clean_exit"] = daemon.returncode == 0
        daemon_final = _last_json(d_out)
        assert _wire(control, {"op": "down"})["ok"] is True
        f_out, f_err = fleet_up.communicate(timeout=120)
        checks["fleet_clean_exit"] = fleet_up.returncode == 0
        fleet_final = _last_json(f_out)
        record["fleet_final"] = fleet_final
        checks["fleet_final_counters"] = (
            fleet_final["replica_restarts"] >= 3   # 1 kill + 2 crash-loop
            and fleet_final["quarantined"] == 1
            and fleet_final["fleet_members"]["s0r2"]["state"]
            == "quarantined"
        )

        # ---- ledger + report + status render -------------------------
        recs = L.PerfLedger(ledger_path).load()
        route_rec = next(
            (r for r in recs if r.get("entry") == "route"), None
        )
        fleet_rec = next(
            (r for r in recs if r.get("entry") == "fleet"), None
        )
        checks["ledger_self_healing_fields"] = (
            route_rec is not None
            and route_rec.get("router_retries", 0) >= 1
            and route_rec.get("hedged_rate") is not None
            and route_rec.get("deadline_exceeded_rate") is not None
            and fleet_rec is not None
            and fleet_rec.get("replica_restarts", 0) >= 3
        )
        record["ledger"] = {
            "route_retries": route_rec and route_rec.get(
                "router_retries"),
            "fleet_restarts": fleet_rec and fleet_rec.get(
                "replica_restarts"),
        }
        # daemon stats mirror the ledger fields
        checks["daemon_stats_scoreboard"] = (
            daemon_final.get("router_retries", 0) >= 1
            and daemon_final.get("membership_reloads", 0) >= 1
            and daemon_final.get("serve_errors") == 0
        )
        # a retried trace hop made it into the qtrace exemplars (the
        # 5s-stalled query is the slowest thing the window saw)
        events = _load_jsonl(
            os.path.join(telem, "router", "events.jsonl")
        )
        checks["trace_attributes_retry_hops"] = any(
            e.get("kind") == "qtrace"
            and any(h.get("retried") for h in e.get("hops", [])
                    if isinstance(h, dict))
            for e in events
        )
        rep = _cli("report", "--fleet", telem, env=env).stdout
        checks["report_renders_supervisor"] = (
            "supervisor [" in rep and "quarantined" in rep
            and "self-healing:" in rep
        )
        watch = _cli("watch", "--fleet", telem, "--once",
                     env=env).stdout
        checks["watch_renders_supervision"] = "supervision:" in watch
        offline = _cli("fleet", "status", "--members", members,
                       env=env)
        checks["fleet_status_offline_roster"] = (
            offline.returncode == 0
            and "members" in _last_json(offline.stdout)
        )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    # ---- verdict ----------------------------------------------------
    record["checks"] = checks
    record["pass"] = all(checks.values())
    line = json.dumps(record)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    return 0 if record["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
