"""com-Orkut-class end-to-end ladder on ONE chip (VERDICT r4 item 5).

SEEDING_r04.json proved the host seeding pass at 100M directed edges; this
script proves the FULL pipeline at that scale on hardware: synthetic graph
build -> ingest/symmetrize/CSR -> conductance seeding -> F init ->
K-blocked CSR fit iterations on the accelerator -> device-side extraction.
Every stage is timed; one JSON line is the artifact.

    python scripts/e2e_ladder.py [n] [m_edges_millions] [k] [iters] [out.json]

Defaults: N=3,000,000 nodes, 50M undirected edges (~100M directed after
symmetrize+dedup), K=256, 5 timed optimizer iterations.

Sizing: the train step holds three (N_pad, K_pad) f32 arrays at peak
(F, grad, F_new) -> 3M x 256 x 4B x 3 ~ 9.2 GB, plus ~1 GB CSR edge
arrays: fits a 16 GB v5e with headroom. K-blocking is forced via
cfg.csr_k_block=128 so the csr_grouped_kb kernel path (the pod-scale
large-K path, BASELINE configs 3-5) is what runs on hardware — on a TPU
backend a silent fallback FAILS the run rather than polluting the artifact.

Scale anchor: BASELINE config 4 (com-Orkut N=3.07M, E=117M); the
reference's own proof-of-scale was its 36-core HDFS cluster run
(/root/reference/codes/bigclam4-7.scala:14,45).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 3_000_000
    m_m = float(sys.argv[2]) if len(sys.argv) > 2 else 50.0
    k = int(sys.argv[3]) if len(sys.argv) > 3 else 256
    iters = int(sys.argv[4]) if len(sys.argv) > 4 else 5
    out_path = sys.argv[5] if len(sys.argv) > 5 else None

    import jax

    if os.environ.get("E2E_CPU"):
        # smoke-test hook: the outer env pins JAX_PLATFORMS to the real
        # TPU and the axon plugin hooks get_backend, so an env override is
        # too late — jax.config before backend init is what works
        jax.config.update("jax_platforms", "cpu")

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.models import BigClamModel
    from bigclam_tpu.ops import extraction, seeding
    from scripts.seeding_bench import build_synthetic

    on_tpu = jax.default_backend() == "tpu"
    sec = {}
    rng = np.random.default_rng(0)

    t0 = time.time()
    g = build_synthetic(n, int(m_m * 1e6), rng)
    sec["graph_build"] = round(time.time() - t0, 1)
    e = g.num_directed_edges

    cfg = BigClamConfig(num_communities=k, csr_k_block=128)

    t0 = time.time()
    seeds = seeding.conductance_seeds(g, cfg)
    sec["seeding"] = round(time.time() - t0, 1)

    t0 = time.time()
    F0 = seeding.init_F(g, seeds, cfg, np.random.default_rng(1)).astype(
        np.float32
    )
    sec["init_F"] = round(time.time() - t0, 1)

    t0 = time.time()
    model = BigClamModel(g, cfg)
    state = model.init_state(F0)
    del F0
    state = model._step(state)          # compile + first step
    jax.block_until_ready(state.F)
    sec["compile_first_step"] = round(time.time() - t0, 1)
    # csr_fused_kb is the default K-blocked path since r17; csr_grouped_kb
    # is the split suite (csr_fused=False)
    if on_tpu and model.engaged_path not in (
        "csr_fused_kb", "csr_grouped_kb",
    ):
        raise RuntimeError(
            f"K-blocked path did not engage on TPU: {model.engaged_path} "
            f"({model.path_reason})"
        )
    llh0 = float(state.llh)

    t0 = time.time()
    llh_traj = []
    for _ in range(iters):
        state = model._step(state)
        # state.llh is the LLH of the step's INPUT F. Append the UNFORCED
        # device scalar — a float() here would sync every iteration and
        # distort the timed loop; the conversion happens after the single
        # block_until_ready the measurement already pays
        llh_traj.append(state.llh)
    jax.block_until_ready(state.F)
    dt = time.time() - t0
    llh_traj = [float(v) for v in llh_traj]
    sec["fit_iters"] = round(dt, 1)
    eps = e * iters / dt

    t0 = time.time()
    comms = extraction.extract_communities_device(
        state.F, g, num_communities=k
    )
    sec["extraction"] = round(time.time() - t0, 1)

    # health criterion: the simultaneous Jacobi update (reference
    # semantics) carries NO per-iteration global-LLH guarantee — each
    # node's Armijo acceptance is against the OTHERS' old rows, and the
    # combined move can overshoot for an iteration before recovering
    # (observed at N=200K: a one-iteration dip at iter 3, then recovery
    # well above the start — the r06 CPU smoke caught the old strict
    # last>=first gate sampling exactly that dip). The gate therefore
    # asks what the optimizer does guarantee on a healthy pipeline: the
    # best LLH seen over the run improves on the initial one, and every
    # value is finite.
    llh_best = max(llh_traj) if llh_traj else llh0
    finite = all(np.isfinite(v) for v in llh_traj + [llh0])
    rec = {
        "bench": "e2e-ladder",
        "config": f"synthetic N={n} 2E={e} K={k} iters={iters}",
        "backend": jax.default_backend(),
        "path": model.engaged_path,
        "seconds": sec,
        "total_seconds": round(sum(sec.values()), 1),
        "fit_edges_per_sec": round(eps, 1),
        "llh_first": llh0,
        "llh_last": float(state.llh),
        "llh_trajectory": llh_traj,
        "llh_best": llh_best,
        "llh_monotone": bool(
            all(b >= a for a, b in zip([llh0] + llh_traj, llh_traj))
        ),
        "num_communities_extracted": len(comms),
        "pass": bool(
            (not on_tpu or model.engaged_path == "csr_grouped_kb")
            and finite
            and llh_best > llh0
        ),
    }
    line = json.dumps(rec)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    return 0 if rec["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
