"""Membership-serving gate (ISSUE 14): prove on CPU, fast enough for CI,
that the serving subsystem delivers its contract:

  families          all three query families answer correctly against a
                    fitted planted-anchor snapshot (communities_of ==
                    the extraction row read, members_of == the inverted
                    index == extract_communities, suggest_for leads with
                    the trained community)
  zipf_load         a synthetic Zipf query mix at batch QPS, recording
                    p99 latency, QPS/chip, and cache hit rate (the
                    Zipf-aware mass-share cache must land a high hit
                    rate on the head-skewed members_of traffic)
  foldin_quality    hold out a node subset, fold their rows back in from
                    a cold (neighbor-mean) start against the frozen
                    remainder: the global LLH must land within the
                    planted-anchor band of the FULL-REFIT LLH, and
                    warm-started fold-in must recover the trained rows
  hot_swap          publishing a new snapshot mid-load and hot-swapping
                    drops ZERO queries, and answers flip to the new
                    generation
  ledger            the serve run's p99/QPS land in the perf ledger; an
                    identical re-run diffs PASS, and a fit record can
                    never baseline a serve record

Emits one JSON artifact (SERVE_r18.json); exit 0 iff every check passes.

    python scripts/serve_gate.py [out.json]
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N = 360
K = 12
P_IN = 0.7
FOLDIN_BAND = 0.05          # |1 - LLH_foldin / LLH_refit| ceiling
LOAD_QUERIES = 1200
ZIPF_A = 1.3


def _zipf_rank(rng, n, size):
    """Zipf-distributed ranks in [0, n) (rejection past n)."""
    out = rng.zipf(ZIPF_A, size=size * 2) - 1
    out = out[out < n]
    while out.size < size:
        more = rng.zipf(ZIPF_A, size=size) - 1
        out = np.concatenate([out, more[more < n]])
    return out[:size]


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else None

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.models import BigClamModel
    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.obs import RunTelemetry, install, uninstall
    from bigclam_tpu.obs import ledger as L
    from bigclam_tpu.ops import extraction
    from bigclam_tpu.serve.server import MembershipServer
    from bigclam_tpu.serve.snapshot import (
        ServingSnapshot,
        publish_snapshot,
    )
    from bigclam_tpu.spec import interpreter as spec

    import tempfile

    workdir = tempfile.mkdtemp(prefix="serve_gate_")
    checks = {}
    record = {"gate": "serve", "n": N, "k": K, "p_in": P_IN}

    # ---- fit + publish the planted anchor ---------------------------
    rng = np.random.default_rng(7)
    g, truth = sample_planted_graph(N, K, p_in=P_IN, rng=rng)
    cfg = BigClamConfig(num_communities=K, max_iters=500)
    model = BigClamModel(g, cfg)
    t0 = time.perf_counter()
    res = model.fit(model.random_init())
    record["fit_s"] = round(time.perf_counter() - t0, 3)
    record["fit_llh"] = res.llh
    record["fit_iters"] = res.num_iters
    snapdir = os.path.join(workdir, "snaps")
    publish_snapshot(
        snapdir, step=res.num_iters, F=res.F, raw_ids=g.raw_ids,
        num_edges=g.num_edges, cfg=cfg, meta={"llh": res.llh},
    )

    # ---- families correct -------------------------------------------
    snap = ServingSnapshot.load(snapdir)
    comms = extraction.extract_communities(res.F, g)
    members_ok = all(
        snap.members_of(c).tolist() == comms.get(c, []) for c in range(K)
    )
    delta = extraction.delta_threshold(g.num_nodes, g.num_edges)
    mask = extraction.membership_mask(res.F, delta)
    server = MembershipServer(
        snapdir, graph=g, budget_s=0.002, max_batch=32, cache_slots=4
    )
    rows_ok = True
    suggest_ok = True
    for u in range(0, N, 37):
        r = server.query({"family": "communities_of", "u": int(g.raw_ids[u])})
        got = sorted(c for c, _ in r["communities"])
        rows_ok &= got == np.nonzero(mask[u])[0].tolist()
        s = server.query({"family": "suggest_for", "u": int(g.raw_ids[u])})
        top_trained = (
            int(np.argmax(res.F[u])) if res.F[u].max() > 0 else None
        )
        if top_trained is not None and s.get("suggested"):
            suggest_ok &= s["suggested"][0][0] == top_trained
    checks["families_members_of"] = bool(members_ok)
    checks["families_communities_of"] = bool(rows_ok)
    checks["families_suggest_leads_with_trained"] = bool(suggest_ok)

    # ---- Zipf load with telemetry + ledger --------------------------
    ledger_path = os.path.join(workdir, "ledger.jsonl")
    qrng = np.random.default_rng(11)
    # members_of targets: Zipf rank over communities ORDERED BY MASS
    # SHARE — the head-skew the cache is keyed for
    by_share = snap.top_mass_communities(K)
    n_members = int(LOAD_QUERIES * 0.45)
    n_comm = int(LOAD_QUERIES * 0.45)
    n_suggest = LOAD_QUERIES - n_members - n_comm
    queries = (
        [
            {"family": "members_of",
             "c": int(by_share[r])}
            for r in _zipf_rank(qrng, K, n_members)
        ]
        + [
            {"family": "communities_of",
             "u": int(g.raw_ids[int(r)])}
            for r in _zipf_rank(qrng, N, n_comm)
        ]
        + [
            {"family": "suggest_for",
             "u": int(g.raw_ids[int(r)])}
            for r in _zipf_rank(qrng, N, n_suggest)
        ]
    )
    qrng.shuffle(queries)

    def run_load(tdir):
        tel = install(RunTelemetry(
            tdir, entry="serve", quiet=True, device_memory=False,
            ledger_path=ledger_path,
        ))
        try:
            # warm the fold-in compile caches, then measure clean
            server.run_queries(queries[:32])
            server.reset_stats()
            t0 = time.perf_counter()
            results = server.run_queries(queries)
            wall = time.perf_counter() - t0
            stats = server.stats()
            tel.set_final(stats)
            return results, stats, wall
        finally:
            tel.finalize()
            uninstall(tel)

    results, stats, wall = run_load(os.path.join(workdir, "telem1"))
    record["zipf"] = {
        "queries": stats["serve_queries"],
        "errors": stats["serve_errors"],
        "wall_s": round(wall, 3),
        "p50_ms": round(stats["serve_p50_s"] * 1e3, 3),
        "p99_ms": round(stats["serve_p99_s"] * 1e3, 3),
        "qps_per_chip": round(stats["serve_qps"], 1),
        "cache_hit_rate": stats["cache_hit_rate"],
        "mix": stats["serve_mix"],
        "batches": stats["batches"],
    }
    checks["zipf_all_answered"] = (
        stats["serve_queries"] == LOAD_QUERIES
        and stats["serve_errors"] == 0
    )
    checks["zipf_p99_recorded"] = stats["serve_p99_s"] > 0
    # 4 cache slots on 12 communities under a=1.3 Zipf: the head mass
    # dominates, so the share-keyed cache must land a clear majority of
    # members_of hits
    checks["zipf_cache_hit_rate"] = stats["cache_hit_rate"] >= 0.5

    # identical re-run -> ledger diff must PASS; a fit record must never
    # become a serve baseline
    _, stats2, _ = run_load(os.path.join(workdir, "telem2"))
    led = L.PerfLedger(ledger_path)
    recs = led.load()
    serve_recs = [r for r in recs if r.get("entry") == "serve"]
    checks["ledger_two_serve_records"] = len(serve_recs) == 2
    base = led.baseline_for(serve_recs[1], recs)
    checks["ledger_baseline_found"] = (
        base is not None and base.get("run") == serve_recs[0].get("run")
    )
    diff = L.diff_records(serve_recs[0], serve_recs[1], tolerance=5.0)
    # tolerance 5.0: this pins the WIRING (serve p99 is verdicted and an
    # identical re-run passes); band arithmetic is unit-tested
    checks["ledger_identical_rerun_passes"] = not diff["regression"]
    checks["ledger_p99_verdicted"] = any(
        c["metric"] == "serve_p99_s" and c.get("verdicted")
        for c in diff["checks"]
        if not c.get("skipped")
    )
    fit_rec = L.build_record({
        "run": "fitrun", "entry": "fit", "pid": 0, "processes": 1,
        "wall_s": 1.0, "fingerprint": serve_recs[0].get("host") and {
            "host": serve_recs[0]["host"],
            "backend": serve_recs[0].get("backend"),
            "device_kind": serve_recs[0].get("device_kind"),
        } or {},
        "compiles": {"count": 0, "by_key": serve_recs[0].get(
            "cfg_keys", [])},
        "spans": {"seconds": {}},
        "final": {"llh": res.llh, "n": N, "edges": g.num_edges, "k": K},
    })
    led.append(fit_rec)
    checks["ledger_fit_never_baselines_serve"] = (
        L.match_key(fit_rec) != L.match_key(serve_recs[0])
    )

    # ---- fold-in quality vs full refit ------------------------------
    hrng = np.random.default_rng(5)
    held = np.sort(hrng.choice(N, size=N // 10, replace=False))
    F_held = np.array(res.F)
    F_held[held] = 0.0
    state_held = model.init_state(F_held)
    rows, fold_llh, fold_iters = model.foldin_rows(
        state_held, held, init="mean", conv_tol=1e-7, max_iters=1000
    )
    F_rec = np.array(F_held)
    F_rec[held] = rows
    llh_foldin = float(
        spec.loglikelihood(F_rec, F_rec.sum(0), g, cfg)
    )
    llh_refit = res.llh
    rel = abs(1.0 - llh_foldin / llh_refit)
    record["foldin"] = {
        "held_out": len(held),
        "llh_foldin": llh_foldin,
        "llh_full_refit": llh_refit,
        "rel_gap": round(rel, 5),
        "band": FOLDIN_BAND,
        "iters_max": int(fold_iters.max()),
    }
    checks["foldin_llh_within_refit_band"] = rel <= FOLDIN_BAND
    # warm-started fold-in recovers the trained rows (fixed point)
    state_full = model.init_state(res.F)
    wrows, _, _ = model.foldin_rows(
        state_full, held, init="own", conv_tol=1e-8, max_iters=500
    )
    recov_err = float(np.abs(wrows - res.F[held]).max())
    record["foldin"]["trained_row_recovery_err"] = recov_err
    checks["foldin_recovers_trained_rows"] = recov_err <= 1e-2

    # ---- hot swap mid-load drops zero queries -----------------------
    F2 = np.roll(res.F, 1, axis=1)
    n_load = 400
    load_results = []

    def background_load():
        load_results.extend(
            server.run_queries(
                [{"family": "members_of", "c": i % K}
                 for i in range(n_load)]
            )
        )

    server.reset_stats()
    swaps_before = server.stats()["snapshot_swaps"]
    t = threading.Thread(target=background_load)
    t.start()
    publish_snapshot(
        snapdir, step=res.num_iters + 1, F=F2, raw_ids=g.raw_ids,
        num_edges=g.num_edges, cfg=cfg,
    )
    new_step = server.hot_swap()
    t.join(timeout=120.0)
    stats3 = server.stats()
    answered = sum(1 for r in load_results if "members" in r)
    snap2 = ServingSnapshot.load(snapdir)
    after = server.query({"family": "members_of", "c": 0})
    record["hot_swap"] = {
        "load_queries": n_load,
        "answered": answered,
        "errors": stats3["serve_errors"],
        "new_step": new_step,
    }
    checks["hot_swap_zero_dropped"] = (
        not t.is_alive()
        and answered == n_load
        and stats3["serve_errors"] == 0
    )
    checks["hot_swap_generation_advanced"] = (
        new_step == res.num_iters + 1
        and stats3["snapshot_swaps"] == swaps_before + 1
    )
    checks["hot_swap_answers_flip"] = (
        after["members"] == snap2.members_of(0).tolist()
        and snap2.step == new_step
    )
    server.close()

    # ---- verdict ----------------------------------------------------
    record["checks"] = checks
    record["pass"] = all(checks.values())
    line = json.dumps(record)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    return 0 if record["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
