"""Streaming-ingest benchmark -> INGEST artifact (ISSUE 3 acceptance).

Measures the graph store's three claims on a synthetic >= 10M-edge SNAP
file, with numbers instead of folklore:

1. BOUNDED RSS: the out-of-core compile (graph/store.compile_graph_cache)
   holds O(chunk + bucket + N) host memory, never O(file). Measured as the
   sampled peak-RSS DELTA over the pre-ingest baseline (utils/profiling
   IngestProfile, sampled at chunk/bucket granularity inside the stages)
   and gated against an EXPLICIT budget model built from the configured
   knobs: ~12 bytes of tokenizer transient per chunk byte (the cost of
   numpy's split-based parse, measured, not assumed) + a few transients of
   one dedup bucket (16 B * 2E/S directed pairs) + a few copies of the
   8 B/node id table + interpreter slack. The seed parser's footprint on
   the same file is ~12 * file_bytes (whole file + one Python token per
   integer) — the artifact records the delta against both, and O(file)
   behavior fails the budget by an order of magnitude at any real scale.
   A second compile at 4x the chunk budget is recorded for reference (its
   baseline is polluted by allocator retention from the first run, so only
   the first, clean-baseline delta is gated).
2. CACHED RELOAD: GraphStore.load_graph (binary npy blobs, optional crc
   verify, no parse/remap/dedup) must be >= 10x faster than the text parse
   (build_graph on the same file — native C parser when built, else the
   streaming numpy path). Gated on the crc-VERIFIED reload, the default
   path; the verify=False mmap fast path is recorded too.
3. BIT IDENTITY: the reloaded graph equals build_graph's output exactly.

Deliberately jax-free (the ingest path's budget is host RAM; importing jax
would both inflate the baseline and hide regressions behind its allocator).

    python scripts/ingest_bench.py [--edges 12000000] [--out INGEST_r07.json]
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from bigclam_tpu.graph.ingest import build_graph
from bigclam_tpu.graph.store import GraphStore, compile_graph_cache
from bigclam_tpu.utils.profiling import IngestProfile, current_rss_bytes


def synth_edge_file(path: str, edges: int, nodes: int, seed: int = 0) -> int:
    """Write a synthetic SNAP edge list (uniform random pairs; dups and
    self-loops land naturally) in 1M-edge slabs, streaming."""
    rng = np.random.default_rng(seed)
    written = 0
    with open(path, "w") as f:
        f.write(f"# synthetic ingest bench: {edges} lines, {nodes} ids\n")
        while written < edges:
            m = min(1_000_000, edges - written)
            pairs = rng.integers(0, nodes, size=(m, 2), dtype=np.int64)
            f.write(
                "\n".join(f"{u} {v}" for u, v in pairs.tolist()) + "\n"
            )
            written += m
    return os.path.getsize(path)


def timed_compile(text, cache_dir, num_shards, chunk_bytes, workers):
    prof = IngestProfile()
    t0 = time.perf_counter()
    store = compile_graph_cache(
        text, cache_dir, num_shards=num_shards, chunk_bytes=chunk_bytes,
        workers=workers, profile=prof,
    )
    seconds = time.perf_counter() - t0
    rep = prof.report()
    return store, {
        "chunk_bytes": chunk_bytes,
        "seconds": round(seconds, 2),
        "edges_per_sec": rep.get("edges_per_sec"),
        "edges_per_sec_parse": rep.get("edges_per_sec_parse"),
        "stage_seconds": rep["seconds"],
        "rss": rep["rss"],
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--edges", type=int, default=12_000_000)
    ap.add_argument("--nodes", type=int, default=None,
                    help="raw id space (default edges // 4)")
    ap.add_argument("--shards", type=int, default=16)
    ap.add_argument("--chunk-bytes", type=int, default=4 << 20,
                    help="primary chunk budget (a 4x larger second run is "
                    "recorded for reference)")
    ap.add_argument("--workers", type=int, default=0)
    ap.add_argument("--out", default="INGEST_r07.json")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh tempdir, removed)")
    args = ap.parse_args()
    if args.edges < 10_000_000:
        print(f"note: --edges {args.edges} < the 10M acceptance floor",
              file=sys.stderr)
    nodes = args.nodes or args.edges // 4

    work = args.workdir or tempfile.mkdtemp(prefix="ingest_bench_")
    os.makedirs(work, exist_ok=True)
    text = os.path.join(work, "synth.txt")
    try:
        t0 = time.perf_counter()
        file_bytes = synth_edge_file(text, args.edges, nodes)
        gen_s = time.perf_counter() - t0
        print(f"[ingest_bench] wrote {file_bytes >> 20} MiB "
              f"({args.edges} lines) in {gen_s:.1f}s", file=sys.stderr)

        rss0 = current_rss_bytes()
        # --- compile at the primary budget and at 4x: RSS ~ chunk ---
        store, small = timed_compile(
            text, os.path.join(work, "cache"), args.shards,
            args.chunk_bytes, args.workers,
        )
        _, big = timed_compile(
            text, os.path.join(work, "cache4x"), args.shards,
            4 * args.chunk_bytes, args.workers,
        )

        # --- cached reload vs text parse ---
        t0 = time.perf_counter()
        g_cache = store.load_graph()              # crc-verified
        reload_verified_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        g_cache = store.load_graph(verify=False)  # mmap fast path
        reload_s = time.perf_counter() - t0

        native = True
        try:
            import bigclam_tpu.graph.native  # noqa: F401
        except ImportError:
            native = False
        t0 = time.perf_counter()
        g_text = build_graph(text)
        parse_s = time.perf_counter() - t0

        identical = (
            np.array_equal(g_cache.indptr, g_text.indptr)
            and np.array_equal(g_cache.indices, g_text.indices)
            and np.array_equal(g_cache.raw_ids, g_text.raw_ids)
        )
        speedup = parse_s / max(reload_s, 1e-9)
        speedup_verified = parse_s / max(reload_verified_s, 1e-9)

        # bounded-RSS verdict against the explicit budget model: tokenizer
        # transient (12 B/chunk byte) + dedup-bucket transients + id-table
        # copies + interpreter slack — every term a configured knob or a
        # graph property, none a file property. The seed parser's O(file)
        # footprint (~12 B/file byte) is the contrast line.
        delta_small = small["rss"]["delta_bytes"]
        delta_big = big["rss"]["delta_bytes"]
        bucket_bytes = 16 * store.num_directed_edges // args.shards
        idtable_bytes = 8 * store.num_nodes
        budget = (
            12 * args.chunk_bytes
            + 6 * bucket_bytes
            + 4 * idtable_bytes
            + (96 << 20)
        )
        seed_equiv = 12 * file_bytes
        rss_bounded = delta_small <= budget and delta_small < seed_equiv / 4

        record = {
            "metric": "ingest",
            "synthetic": {
                "lines": args.edges,
                "raw_id_space": nodes,
                "file_bytes": file_bytes,
                "gen_seconds": round(gen_s, 2),
            },
            "graph": {
                "num_nodes": store.num_nodes,
                "num_directed_edges": store.num_directed_edges,
                "num_shards": store.num_shards,
            },
            "compile": {"chunk": small, "chunk_4x": big},
            "rss_baseline_bytes": rss0,
            "rss_bounded": bool(rss_bounded),
            "rss_budget_bytes": budget,
            "rss_budget_terms": {
                "tokenizer_12x_chunk": 12 * args.chunk_bytes,
                "dedup_bucket_6x": 6 * bucket_bytes,
                "id_table_4x": 4 * idtable_bytes,
                "slack": 96 << 20,
            },
            "rss_seed_equivalent_bytes": seed_equiv,
            "rss_delta_over_seed_equivalent": round(
                delta_small / seed_equiv, 4
            ),
            "rss_delta_over_file": round(delta_small / file_bytes, 4),
            "rss_delta_4x_chunk_bytes": delta_big,
            "reload": {
                "seconds": round(reload_s, 3),
                "seconds_verified": round(reload_verified_s, 3),
                "text_parse_seconds": round(parse_s, 3),
                "text_parser": "native" if native else "numpy-stream",
                "speedup": round(speedup, 1),
                "speedup_verified": round(speedup_verified, 1),
            },
            "bit_identical": bool(identical),
            "pass": bool(
                rss_bounded and identical and speedup_verified >= 10.0
            ),
        }
        out = args.out
        with open(out, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
        print(json.dumps({k: record[k] for k in
                          ("rss_bounded", "bit_identical", "pass")}
                         | {"speedup": record["reload"]["speedup"],
                            "rss_delta_mb": delta_small >> 20}))
        return 0 if record["pass"] else 1
    finally:
        if args.workdir is None:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
