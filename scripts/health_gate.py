"""Model-health diagnostics gate (ISSUE 8 satellite): prove, on CPU, that
the device-fused health layer detects the failure modes it exists for —
deterministically — and stays silent on a healthy fit.

Four planted scenarios, each a REAL fit with the full telemetry stack:

  healthy      default-tolerance dense fit           -> zero anomalies
  divergence   sign-flipped single-candidate Armijo  -> `divergence` fires,
               ladder walks downhill: LLH worsens       run stays NaN-free
               geometrically (slope blow-up), finite    (no nonfinite event)
  plateau      conv_tol=0 fit run far past            -> `plateau` fires
               convergence (the stop rule never can)
  cap_pressure sparse sharded (dp=2) with a starved   -> `cap_pressure`
               comm cap: admission overflows the        fires; sparse_comm
               sparse allreduce -> dense-psum fallback   events recorded

plus the acceptance cross-checks: every events.jsonl schema-validates,
health-off reproduces the health-on trajectory bit-for-bit, and `cli
report` / `cli watch` render the health sections (report --json parses).

    python scripts/health_gate.py [HEALTH_r12.json]

Exit 0 iff every check passes. The committed artifact is the proof the
detectors and their planted failures agree at the commit that shipped
them; the same recipes run in tier-1 (tests/test_health.py).
"""

import json
import math
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else None

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    from bigclam_tpu.utils.dist import request_cpu_devices

    request_cpu_devices(2)

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.models import BigClamModel, SparseBigClamModel
    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.obs import (
        RunTelemetry,
        install,
        uninstall,
        validate_events_file,
    )
    from bigclam_tpu.obs.report import render, render_json
    from bigclam_tpu.obs.telemetry import EVENTS_NAME
    from bigclam_tpu.obs.watch import render_frame
    from bigclam_tpu.parallel import SparseShardedBigClamModel, make_mesh
    from bigclam_tpu.utils.profiling import step_time

    g, _ = sample_planted_graph(
        240, 4, p_in=0.3, rng=np.random.default_rng(0)
    )
    F0 = np.random.default_rng(1).uniform(0.1, 1.0, size=(g.num_nodes, 4))

    def base_cfg(**kw):
        d = dict(num_communities=4, dtype="float64", max_iters=8,
                 conv_tol=0.0, health_every=1)
        d.update(kw)
        return BigClamConfig(**d)

    checks = {}
    scenarios = {}

    def run_scenario(name, build_and_fit, expect):
        tdir = tempfile.mkdtemp(prefix=f"health_{name}_")
        tel = install(RunTelemetry(tdir, entry=name, quiet=True))
        llh_history = ()
        err = None
        try:
            llh_history = build_and_fit()
        except Exception as e:       # a scenario crashing IS a failure
            err = f"{type(e).__name__}: {e}"
        finally:
            tel.finalize()
            uninstall(tel)
        events = []
        with open(os.path.join(tdir, EVENTS_NAME)) as f:
            for line in f:
                if line.strip():
                    events.append(json.loads(line))
        n, schema_errors = validate_events_file(
            os.path.join(tdir, EVENTS_NAME)
        )
        fired = sorted(
            {e["check"] for e in events if e["kind"] == "anomaly"}
        )
        health_n = sum(1 for e in events if e["kind"] == "health")
        nonfinite = sum(1 for e in events if e["kind"] == "nonfinite")
        finite = all(
            isinstance(v, (int, float)) and math.isfinite(v)
            for v in llh_history
        )
        scenarios[name] = {
            "telemetry_dir": tdir,
            "error": err,
            "events": n,
            "health_samples": health_n,
            "anomalies_fired": fired,
            "anomalies_expected": sorted(expect),
            "nonfinite_events": nonfinite,
            "llh_history_finite": finite,
            "llh_head": [float(f"{v:.6g}") for v in llh_history[:6]],
            "schema_errors": schema_errors[:5],
        }
        checks[f"{name}_schema_valid"] = not schema_errors
        checks[f"{name}_health_sampled"] = health_n > 0
        checks[f"{name}_anomalies_match"] = fired == sorted(expect)
        checks[f"{name}_no_crash"] = err is None
        return tdir

    # --- healthy baseline: fires nothing ---
    def fit_healthy():
        cfg = base_cfg(conv_tol=1e-4, max_iters=100)
        return BigClamModel(g, cfg).fit(F0).llh_history

    healthy_dir = run_scenario("healthy", fit_healthy, expect=[])

    # --- planted divergence: NaN-free slope blow-up ---
    def fit_divergence():
        cfg = base_cfg(alpha=1e9, max_backtracks=0, step_scale=-0.02,
                       rollback_budget=0)
        return BigClamModel(g, cfg).fit(F0).llh_history

    div_dir = run_scenario("divergence", fit_divergence,
                           expect=["divergence"])
    checks["divergence_nan_free"] = (
        scenarios["divergence"]["nonfinite_events"] == 0
        and scenarios["divergence"]["llh_history_finite"]
    )

    # --- planted plateau: flat far past the (disabled) stop rule ---
    def fit_plateau():
        cfg = base_cfg(max_iters=40)
        return BigClamModel(g, cfg).fit(F0).llh_history

    run_scenario("plateau", fit_plateau, expect=["plateau"])

    # --- planted sparse cap pressure: starved comm cap overflows ---
    K = 64
    F0w = np.zeros((g.num_nodes, K))
    F0w[:, :48] = np.random.default_rng(1).uniform(
        0.1, 1.0, size=(g.num_nodes, 48)
    )

    def fit_cap():
        cfg = base_cfg(
            num_communities=K, representation="sparse", sparse_m=8,
            sparse_comm_cap=8, max_iters=4,
        )
        mesh = make_mesh((2, 1), jax.devices()[:2])
        model = SparseShardedBigClamModel(g, cfg, mesh)
        assert model.comm_mode == "sparse", model.comm_mode
        return model.fit(F0w).llh_history

    cap_dir = run_scenario("cap_pressure", fit_cap,
                           expect=["cap_pressure"])
    cap_events = []
    with open(os.path.join(cap_dir, EVENTS_NAME)) as f:
        cap_events = [json.loads(l) for l in f if l.strip()]
    comm = [e for e in cap_events if e["kind"] == "sparse_comm"]
    hp = [e for e in cap_events if e["kind"] == "health"]
    checks["cap_sparse_comm_events"] = bool(comm) and all(
        isinstance(e.get("comm_cap"), int) and e.get("comm_mode")
        for e in comm
    )
    checks["cap_counters_in_health"] = bool(hp) and all(
        "cap_occupancy" in e and "dense_fallback" in e
        and "exchanged_ids" in e for e in hp
    )

    # --- bit-identity: health off reproduces the health-on trajectory ---
    cfg_on = base_cfg(conv_tol=1e-4, max_iters=100)
    cfg_off = cfg_on.replace(health_every=0)
    r_on = BigClamModel(g, cfg_on).fit(F0)
    m_off = BigClamModel(g, cfg_off)
    r_off = m_off.fit(F0)
    checks["health_off_bit_identical"] = bool(
        np.array_equal(r_on.F, r_off.F)
        and r_on.llh_history == r_off.llh_history
    )
    off_state = m_off._step(m_off.init_state(F0))
    checks["health_off_packless"] = off_state.health is None

    # --- step-time delta, informational (the binding <2% pin is the
    # host-bookkeeping measurement in tests/test_health.py) ---
    m_on2 = BigClamModel(g, base_cfg(health_every=10))
    s_on = step_time(m_on2._step, m_on2.init_state(F0), steps=20, warmup=3)
    s_off = step_time(m_off._step, m_off.init_state(F0), steps=20, warmup=3)

    # --- renderers ---
    text, render_errors = render(div_dir)
    checks["report_renders_anomaly"] = (
        render_errors == 0 and "ANOMALIES: divergence" in text
    )
    obj, json_errors = render_json(div_dir)
    checks["report_json_parses"] = (
        json_errors == 0
        and json.loads(json.dumps(obj))["anomalies"][0]["check"]
        == "divergence"
    )
    frame = render_frame(healthy_dir)
    checks["watch_renders"] = "llh" in frame and "grad_norm" in frame

    ok = all(checks.values())
    artifact = {
        "gate": "health_r12",
        "created_unix": round(time.time(), 1),
        "pass": ok,
        "checks": checks,
        "scenarios": scenarios,
        "step_time_health_on_s": round(s_on, 6),
        "step_time_health_off_s": round(s_off, 6),
        "note": (
            "planted divergence/plateau/cap-pressure runs fire exactly "
            "their matching anomaly kind; healthy baseline fires none; "
            "all events schema-valid; health-off bit-identical. The "
            "binding <2% overhead pin at the default cadence lives in "
            "tests/test_health.py (step-time deltas on a 240-node CPU "
            "toy are dominated by run-to-run jitter)."
        ),
    }
    line = json.dumps(artifact, sort_keys=True)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    if not ok:
        bad = sorted(k for k, v in checks.items() if not v)
        print(f"FAILED checks: {bad}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
