"""Quality-mode recovery gate (VERDICT round-3 item 1; criterion
re-grounded round 6 per VERDICT r5 Next #4).

Plants an equal-block AGM at the requested scale, runs the faithful fit
AND the quality-mode schedule from the same conductance-seeded init on the
default backend (TPU when available; blocked-CSR kernels engage), and
prints one JSON line with both scores plus the quality stage's PER-STAGE
wall-clock and transfer counts (QualityResult.stages — the round-6
device-resident pipeline's instrumentation).

    python scripts/quality_gate.py [N] [K] [out.json] [p_in]

The quality schedule runs DEVICE-RESIDENT (fit_quality_device: on-device
kicks, batched label-propagation components, scatter-edit repairs, <= 1 F
download per repair round); set QUALITY_GATE_HOST=1 for the host loop.

Gate criterion (round 6 — gate and adjudication must agree in the
artifact, VERDICT r5 weak #2):

  * p_in >= 0.5 (identifiable regime): quality F1 >= 0.8 — unchanged.
  * p_in < 0.5 (sub-identifiability): final quality LLH within 2% of the
    PLANTED-ANCHOR LLH — the planted F refit under faithful semantics
    (MIDSCALE_ANCHOR_r05.json proved the optimum band is F1-degenerate
    there: the anchor refits to itself at -156.59K while distinct
    re-tilings of the same band score F1 anywhere from 0.74 to 1.0, so
    LLH is what the optimizer can be held to). F1 is still reported.

Note on single-chip sizing: the train step holds three (N_pad, K_pad) f32
arrays at peak (F, grad, F_new), so N*K is bounded by ~HBM/12B on one
chip — at K=5120 that is ~280K nodes on a 16 GB v5e. Larger N at this K
is exactly the sharded-trainer regime (BASELINE configs 3-5).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

LLH_BAND_TOL = 0.02     # quality LLH may sit this far below the anchor


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 60000
    k = int(sys.argv[2]) if len(sys.argv) > 2 else 300
    out_path = sys.argv[3] if len(sys.argv) > 3 else None
    p_in = float(sys.argv[4]) if len(sys.argv) > 4 else 0.15

    # opt-in run telemetry (bigclam_tpu.obs): BIGCLAM_TELEMETRY_DIR=<dir>
    # leaves events.jsonl + run_report.json next to the gate artifact —
    # cycle events, stage seconds (the quality StageProfile forwards), HBM
    # watermarks, and a stall heartbeat for the long anneal/repair fits
    tel = None
    tdir = os.environ.get("BIGCLAM_TELEMETRY_DIR")
    if tdir:
        from bigclam_tpu.obs import RunTelemetry, install

        tel = install(
            RunTelemetry(tdir, entry="quality_gate", heartbeat_s=600.0)
        )
    try:
        return _main(n, k, out_path, p_in, tel)
    finally:
        if tel is not None:
            from bigclam_tpu.obs import uninstall

            tel.finalize()
            uninstall(tel)


def _main(n, k, out_path, p_in, tel=None) -> int:

    import jax

    if os.environ.get("E2E_CPU"):
        # CPU stand-in hook (tunnel-down runs): jax.config before backend
        # init is the mechanism that works under the axon plugin
        jax.config.update("jax_platforms", "cpu")

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.evaluation import avg_f1
    from bigclam_tpu.models import BigClamModel
    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.models.quality import (
        auto_quality_max_p,
        fit_quality,
        fit_quality_device,
    )
    from bigclam_tpu.ops import extraction, seeding

    host_loop = os.environ.get("QUALITY_GATE_HOST") == "1"
    rng = np.random.default_rng(7)
    g, truth = sample_planted_graph(n, k, p_in=p_in, rng=rng)
    cfg = BigClamConfig(num_communities=k, quality_mode=True)
    t0 = time.time()
    seeds = seeding.conductance_seeds(g, cfg)
    F0 = seeding.init_F(g, seeds, cfg, np.random.default_rng(0))
    t_seed = time.time() - t0

    model = BigClamModel(g, cfg, k_multiple=128)

    def score(F):
        com = extraction.extract_communities(np.asarray(F), g)
        return avg_f1(list(com.values()), truth)

    def progress(stage):
        print(f"[gate] {stage}", file=sys.stderr, flush=True)

    def cb(it, llh, extras=None):
        if it % 10 == 0:
            progress(f"iter {it} llh {llh:.4g}")

    progress(f"seeded in {t_seed:.1f}s; fitting faithful "
             f"(path={model.engaged_path})")
    t0 = time.time()
    res_f = model.fit(F0, callback=cb)
    t_faithful = time.time() - t0
    f1_f = score(res_f.F)
    progress(f"faithful done in {t_faithful:.0f}s; quality annealing "
             f"({'host' if host_loop else 'device'} loop)")

    t0 = time.time()
    if host_loop:
        qres = fit_quality(model, F0, callback=cb)
    else:
        qres = fit_quality_device(model, F0, callback=cb)
    t_quality = time.time() - t0
    f1_q = score(qres.fit.F)

    # planted anchor (sub-identifiability criterion): the planted F refit
    # under FAITHFUL semantics — the LLH band the optimizer is held to
    llh_anchor = None
    llh_band = None
    if p_in < 0.5:
        progress("quality done; fitting planted anchor")
        s = float(np.sqrt(-np.log1p(-p_in)))
        F_planted = np.zeros((g.num_nodes, k), np.float64)
        for c, members in enumerate(truth):
            F_planted[members, c] = s
        res_anchor = model.fit(F_planted)
        llh_anchor = float(res_anchor.llh)
        llh_band = (qres.fit.llh - llh_anchor) / abs(llh_anchor)
        passed = bool(llh_band >= -LLH_BAND_TOL)
        criterion = (
            f"llh within {LLH_BAND_TOL:.0%} of planted anchor "
            "(sub-identifiability regime: the optimum band is "
            "F1-degenerate — MIDSCALE_ANCHOR_r05)"
        )
    else:
        passed = bool(f1_q >= 0.8)
        criterion = "quality F1 >= 0.8 (identifiable regime)"

    avg_deg = g.num_directed_edges / max(n, 1)
    rec = {
        "gate": "planted-recovery",
        "config": f"planted AGM N={n} K={k} p_in={p_in} "
                  f"2E={g.num_directed_edges}",
        "criterion": criterion,
        "f1_faithful": round(f1_f, 4),
        "llh_faithful": res_f.llh,
        "f1_quality": round(f1_q, 4),
        "llh_quality": qres.fit.llh,
        "llh_planted_anchor": llh_anchor,
        "llh_band_vs_anchor": (
            round(llh_band, 5) if llh_band is not None else None
        ),
        "quality_loop": "host" if host_loop else "device",
        "quality_cycles": qres.num_cycles,
        "quality_total_iters": qres.total_iters,
        "discrete_moves_accepted": qres.num_repairs,
        "seconds": {
            "seeding": round(t_seed, 1),
            "faithful": round(t_faithful, 1),
            "quality": round(t_quality, 1),
        },
        # round-6 instrumentation: per-stage wall-clock + transfer counts
        # (anneal / repair_detect / repair_polish / atomize_components /
        # atomize_refit / fetches — utils.profiling.StageProfile)
        "quality_stages": qres.stages,
        "engaged_path": model.engaged_path,
        "path_reason": model.path_reason,
        "num_seeds": int(len(seeds)),
        # the relaxed clip the quality run used (shared rule — see
        # models.quality.auto_quality_max_p)
        "quality_max_p_auto": auto_quality_max_p(
            n, avg_deg, floor=cfg.max_p
        ),
        "device": str(jax.devices()[0]),
        "pass": passed,
    }
    if tel is not None:
        tel.set_final(
            {
                "gate": rec["gate"],
                "pass": rec["pass"],
                "f1_quality": rec["f1_quality"],
                "llh_quality": rec["llh_quality"],
            }
        )
    line = json.dumps(rec)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    return 0 if rec["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
