"""MAX_P_ relaxation past the old f32 amp ceiling: the N=5M gate
(VERDICT r4 item 3).

The quality-mode relaxation needs amp = 16N/avg_deg; at N=5M, avg_deg~4
that is 2e7 — beyond the 1e6 ceiling the old `1 - clip(exp(-x))` f32 form
imposed (exp(-x) rounds to 1.0 below x = 2^-24). ops.objective.edge_terms
now forms 1-p as -expm1(-x) (full f32 relative precision at any
amplification), so the auto rule relaxes all the way. This gate PROVES the
regime is functional at the actual scale: same graph, same kicked init,
a few optimizer steps under (a) the old ceiling amp=1e6 and (b) the auto
relaxation amp=2e7, measuring

  * movement of noise-level entries on low-degree nodes (deg <= avg):
    under (a) these are provably frozen (deg * amp < N -> the neighbor
    term cannot beat -sumF), under (b) they move;
  * the accepted-step histogram (TrainState.accept_hist): (b) must accept
    real candidate steps, not the 1e-15 tail.

    python scripts/relax_floor_gate.py [n] [m_edges_millions] [k] [out.json]

Defaults: N=5,000,000, 10M undirected edges, K=16, 3 iterations/config.
Runs on any backend (CPU: ~minutes at f32).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 5_000_000
    m_m = float(sys.argv[2]) if len(sys.argv) > 2 else 10.0
    k = int(sys.argv[3]) if len(sys.argv) > 3 else 16
    out_path = sys.argv[4] if len(sys.argv) > 4 else None

    import jax

    if os.environ.get("E2E_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.models import BigClamModel
    from bigclam_tpu.models.quality import _relax_params, auto_quality_max_p
    from bigclam_tpu.ops import seeding
    from scripts.seeding_bench import build_synthetic

    rng = np.random.default_rng(11)
    t0 = time.time()
    g = build_synthetic(n, int(m_m * 1e6), rng)
    avg_deg = g.num_directed_edges / n
    amp_needed = 16.0 * n / avg_deg
    t_build = time.time() - t0

    deg = np.diff(g.indptr)
    base = BigClamConfig(num_communities=k, quality_mode=True, max_iters=3)
    seeds = seeding.conductance_seeds(g, base)
    F0 = seeding.init_F(g, seeds, base, np.random.default_rng(1)).astype(
        np.float32
    )
    model0 = BigClamModel(g, base)
    _, eps = _relax_params(model0, n)
    kick = np.random.default_rng([11, 0x5EED]).uniform(
        0.0, eps, size=F0.shape
    ).astype(np.float32)
    F_kicked = np.clip(F0 + kick, base.min_f, base.max_f)
    # the measured population: entries that are NOISE-level after the kick
    # (no seeded mass) on LOW-degree nodes — the provably-frozen set under
    # the old ceiling (deg * 1e6 < N <=> deg < 5 here)
    low_deg = deg <= max(int(avg_deg), 1)
    noise_mask = (F0 <= 0.0) & low_deg[:, None]
    del F0, kick

    def run(tag: str, max_p_q: float):
        cfg = base.replace(max_p=max_p_q)
        model = BigClamModel(g, cfg)
        hists = []

        def cb(it, llh, extras=None):
            if extras and extras.get("accept_hist") is not None:
                hists.append(extras["accept_hist"])

        t0 = time.time()
        res = model.fit(F_kicked, callback=cb)
        dt = time.time() - t0
        dF = np.abs(
            np.asarray(res.F[:n], np.float64) - F_kicked.astype(np.float64)
        )
        moved = dF[noise_mask]
        return {
            "max_p": max_p_q,
            "amp": 1.0 / (1.0 - max_p_q),
            "llh": float(res.llh),
            "iters": res.num_iters,
            "seconds": round(dt, 1),
            "noise_move_max": float(moved.max()),
            "noise_move_mean": float(moved.mean()),
            "frac_noise_moved": float((moved > eps).mean()),
            "accept_hist": hists[-1] if hists else None,
        }

    old_ceiling = 1.0 - 1e-6           # what the pre-round-5 clamp allowed
    auto = auto_quality_max_p(n, avg_deg, floor=base.max_p)
    frozen = run("old_ceiling", old_ceiling)
    relaxed = run("auto_relaxed", auto)

    ratio = relaxed["noise_move_max"] / max(frozen["noise_move_max"], 1e-300)
    # pass = the relaxation does on this graph what the mechanism claims:
    # a noise-level entry can GROW to macroscopic membership under the
    # relaxed clip (>= 1000x the kick scale) where the old ceiling holds
    # max growth orders of magnitude lower (>= 100x contrast), and the
    # extra freedom is LLH-productive. Breadth (frac moved) is NOT the
    # claim — in 3 iterations most noise entries of a structureless
    # uniform graph have no gradient signal to ride; what matters is that
    # the clip no longer freezes the ones that do. (The frozen run's own
    # nonzero movement is the 16x headroom in the auto rule: at
    # avg_deg=4, deg*1e6 sits within a constant of N=5M.)
    passed = bool(
        ratio >= 100.0
        and relaxed["noise_move_max"] >= 1000.0 * eps
        and relaxed["llh"] > frozen["llh"]
    )
    rec = {
        "bench": "relax-floor-gate",
        "config": f"synthetic N={n} 2E={g.num_directed_edges} K={k} "
                  f"avg_deg={avg_deg:.2f}",
        "backend": jax.default_backend(),
        "amp_needed": amp_needed,
        "kick_eps": eps,
        "graph_build_seconds": round(t_build, 1),
        "frozen": frozen,
        "relaxed": relaxed,
        "move_ratio_relaxed_over_frozen": ratio,
        "relaxed_llh_above_frozen": bool(relaxed["llh"] > frozen["llh"]),
        "pass": passed,
    }
    line = json.dumps(rec)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    return 0 if rec["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
