"""External anchor for the p_in=0.3 midscale identifiability claim
(VERDICT r4 item 4).

QUALITY_MIDSCALE_r04.json records F1 0.761 at planted N=12K K=500
p_in=0.3 (24-node blocks) and the builder adjudicated it as an AGM
identifiability threshold (p_in=0.5 recovers 1.0). This script grounds
that claim the way the K=300 probe grounded the quality mechanisms
(models/quality.py round-4 diagnosis): initialize AT the planted optimum
and run the FAITHFUL fit.

  * planted-init lands at F1 ~ 1.0 with LLH above the quality run's
    -> the planted structure IS a stable, better optimum: the quality
       mechanisms have a real midscale gap (threshold claim refuted);
  * planted-init degrades toward F1 ~ 0.76 and/or its converged LLH is
    not above the quality run's
    -> the data itself does not prefer the planted structure at this
       p_in: identifiability threshold confirmed.

    python scripts/planted_anchor.py [n] [k] [p_in] [out.json]

Defaults match QUALITY_MIDSCALE_r04: N=12000, K=500, p_in=0.3 (same
sampler seed 7 -> same graph).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

QUALITY_MIDSCALE_LLH = -173787.828125   # QUALITY_MIDSCALE_r04.json
QUALITY_MIDSCALE_F1 = 0.761


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 12000
    k = int(sys.argv[2]) if len(sys.argv) > 2 else 500
    p_in = float(sys.argv[3]) if len(sys.argv) > 3 else 0.3
    out_path = sys.argv[4] if len(sys.argv) > 4 else None

    import jax

    if os.environ.get("E2E_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.evaluation import avg_f1
    from bigclam_tpu.models import BigClamModel
    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.ops import extraction
    from bigclam_tpu.spec import interpreter as spec

    rng = np.random.default_rng(7)       # quality_gate.py's sampler seed
    g, truth = sample_planted_graph(n, k, p_in=p_in, rng=rng)
    cfg = BigClamConfig(num_communities=k)       # faithful parity semantics

    # planted F: one shared community per within-block pair ->
    # P(edge) = 1 - exp(-s^2) = p_in at s = sqrt(-log(1-p_in))
    s = float(np.sqrt(-np.log1p(-p_in)))
    F_planted = np.zeros((g.num_nodes, k), np.float64)
    for c, members in enumerate(truth):
        F_planted[members, c] = s

    model = BigClamModel(g, cfg)
    llh_at_planted = float(
        spec.loglikelihood(F_planted, F_planted.sum(0), g, cfg)
    )

    t0 = time.time()
    res = model.fit(F_planted)
    dt = time.time() - t0

    delta = extraction.delta_threshold(g.num_nodes, g.num_edges)
    comms = extraction.extract_communities(res.F, g, delta)
    f1 = avg_f1([set(c) for c in comms.values()], [set(t) for t in truth])

    stayed = f1 >= 0.95
    rec = {
        "gate": "planted-init anchor (midscale identifiability)",
        "config": f"planted AGM N={n} K={k} p_in={p_in} "
                  f"2E={g.num_directed_edges}",
        "backend": jax.default_backend(),
        "planted_strength": s,
        "llh_at_planted_init": llh_at_planted,
        "llh_after_faithful_fit": float(res.llh),
        "f1_after_faithful_fit": float(f1),
        "num_iters": res.num_iters,
        "seconds": round(dt, 1),
        "quality_run_llh": QUALITY_MIDSCALE_LLH,
        "quality_run_f1": QUALITY_MIDSCALE_F1,
        "planted_beats_quality_llh": float(res.llh) > QUALITY_MIDSCALE_LLH,
        # verdict semantics, not pass/fail: which story does the data tell?
        "verdict": (
            "mechanism-gap: planted F is a stable fixed point well above "
            "the quality run's plateau"
            if stayed and float(res.llh) > QUALITY_MIDSCALE_LLH
            else "threshold-confirmed: data does not prefer planted structure"
        ),
    }
    line = json.dumps(rec)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
