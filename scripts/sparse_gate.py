"""Sparse-representation gate (ISSUE 7): prove on CPU, fast enough for
CI, that the top-M affiliation representation delivers its contract:

  parity            M >= K sparse trajectory matches the dense trainer
                    (LLH histories within float band)
  exchange          the sharded sparse allreduce moves only touched
                    community ids (counter << K, no dense fallback) and
                    its result is bit-identical to the forced dense psum
  K-scaling         sparse step TIME and state BYTES stay ~flat in K at
                    fixed M on the same graph, while the dense step
                    grows with K — the "K becomes a capacity knob" claim
  memory            affiliation-state bytes at K in {1000, 5000}, M=64,
                    with the dense (N*K*4) comparison recorded

Emits one JSON artifact line (SPARSE_r11.json); exit 0 iff every check
passes.

    python scripts/sparse_gate.py [out.json]
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _median_step_seconds(model, state, steps=4, warmup=2):
    import jax

    for _ in range(warmup):
        state = model._step(state)
    jax.block_until_ready(state.F)
    times = []
    for _ in range(steps):
        t0 = time.perf_counter()
        state = model._step(state)
        jax.block_until_ready(state.F)
        times.append(time.perf_counter() - t0)
    return statistics.median(times), state


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else None

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    from bigclam_tpu.utils.dist import request_cpu_devices

    request_cpu_devices(8)

    from bench import roofline_model, roofline_model_sparse
    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.models import BigClamModel, SparseBigClamModel
    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.parallel import SparseShardedBigClamModel, make_mesh

    checks = {}
    record = {"gate": "sparse-representation"}

    # ---------------------------------------------------- parity (M >= K)
    g_s, _ = sample_planted_graph(
        240, 4, p_in=0.3, rng=np.random.default_rng(0)
    )
    kp = 4
    cfg_d = BigClamConfig(
        num_communities=kp, dtype="float64", max_iters=20, conv_tol=1e-6,
        use_pallas=False, use_pallas_csr=False,
    )
    F0p = np.random.default_rng(1).uniform(
        0.1, 1.0, size=(g_s.num_nodes, kp)
    )
    rd = BigClamModel(g_s, cfg_d).fit(F0p)
    rs = SparseBigClamModel(
        g_s, cfg_d.replace(representation="sparse", sparse_m=kp)
    ).fit(F0p)
    llh_rel = abs(1.0 - rs.llh / rd.llh)
    checks["parity_m_ge_k"] = (
        rs.num_iters == rd.num_iters and llh_rel < 1e-9
        and np.allclose(rs.F, rd.F, rtol=1e-8, atol=1e-10)
    )
    record["parity"] = {
        "config": f"planted AGM N={g_s.num_nodes} K={kp} M={kp}",
        "dense_llh": rd.llh,
        "sparse_llh": rs.llh,
        "llh_rel_err": llh_rel,
        "iters": [rd.num_iters, rs.num_iters],
    }

    # ------------------------------------- touched-ids-only exchange check
    g_x, truth = sample_planted_graph(
        2048, 512, p_in=0.6, rng=np.random.default_rng(2)
    )
    kx, mx = 512, 16
    F0x = np.zeros((g_x.num_nodes, kx))
    for c, nodes in enumerate(truth):
        F0x[nodes, c] = 1.0
    cfg_x = BigClamConfig(
        num_communities=kx, dtype="float64", max_iters=4, conv_tol=0.0,
        use_pallas=False, use_pallas_csr=False,
        representation="sparse", sparse_m=mx,
    )
    mesh = make_mesh((8, 1), jax.devices())
    m_sp = SparseShardedBigClamModel(g_x, cfg_x, mesh)
    st = m_sp.init_state(F0x)
    for _ in range(3):
        st = m_sp._step(st)
    exchanged, fell_back = m_sp.last_comm(st)
    r_sp = m_sp.fit(F0x)
    m_ps = SparseShardedBigClamModel(
        g_x, cfg_x.replace(sparse_dense_fallback=0.0), mesh
    )
    r_ps = m_ps.fit(F0x)
    checks["sparse_collective_engaged"] = (
        m_sp.engaged_path == "sparse_xla_spall"
    )
    checks["exchange_touched_only"] = (
        not fell_back and 0 < exchanged <= m_sp.comm_cap
        and exchanged < kx // 4
    )
    checks["sparse_allreduce_equals_dense_psum"] = bool(
        np.array_equal(r_sp.F, r_ps.F)
        and r_sp.llh_history == r_ps.llh_history
    )
    record["exchange"] = {
        "config": f"planted AGM N={g_x.num_nodes} K={kx} M={mx} dp=8",
        "exchanged_ids_max": exchanged,
        "cap": m_sp.comm_cap,
        "k": kx,
        "dense_fallback_steps": int(fell_back),
        "path": m_sp.engaged_path,
    }

    # ------------------------------ K-scaling: flat in K at fixed M
    g_k, truth_k = sample_planted_graph(
        10_000, 1000, p_in=0.5, rng=np.random.default_rng(3)
    )
    M = 64
    times, nbytes, dense_times = {}, {}, {}
    for k in (1000, 5000):
        F0k = np.zeros((g_k.num_nodes, k), np.float64)
        for c, nodes in enumerate(truth_k):
            F0k[nodes, c] = 1.0
        base = BigClamConfig(
            num_communities=k, dtype="float64", max_iters=4, conv_tol=0.0,
            use_pallas=False, use_pallas_csr=False,
        )
        ms = SparseBigClamModel(
            g_k, base.replace(representation="sparse", sparse_m=M)
        )
        ss = ms.init_state(F0k)
        times[k], ss = _median_step_seconds(ms, ss)
        nbytes[k] = ms.state_nbytes(ss)
        md = BigClamModel(g_k, base)
        sd = md.init_state(F0k)
        dense_times[k], _ = _median_step_seconds(md, sd, steps=2, warmup=1)
    sparse_time_ratio = times[5000] / times[1000]
    dense_time_ratio = dense_times[5000] / dense_times[1000]
    sparse_bytes_ratio = nbytes[5000] / nbytes[1000]
    checks["sparse_step_time_flat_in_k"] = sparse_time_ratio < 2.0
    checks["dense_step_time_grows_in_k"] = dense_time_ratio > 2.0
    checks["dense_grows_faster_than_sparse"] = (
        dense_time_ratio > 1.5 * sparse_time_ratio
    )
    record["k_scaling"] = {
        "config": f"planted AGM N={g_k.num_nodes} "
                  f"2E={g_k.num_directed_edges} M={M}, K in [1000, 5000]",
        "sparse_step_s": {str(k): round(v, 4) for k, v in times.items()},
        "dense_step_s": {str(k): round(v, 4) for k, v in dense_times.items()},
        "sparse_time_ratio": round(sparse_time_ratio, 3),
        "dense_time_ratio": round(dense_time_ratio, 3),
        "model_bytes_per_edge": {
            "sparse_m64": roofline_model_sparse(M)["bytes_per_edge_iter"],
            "dense_k1000": roofline_model(1000)["bytes_per_edge_iter"],
            "dense_k5000": roofline_model(5000)["bytes_per_edge_iter"],
        },
    }

    # ----------------------------------------- memory: M not K (measured)
    dense_bytes = {k: 10_000 * k * 4 for k in (1000, 5000)}
    # one check, two clauses: sparse state bytes ~flat in K AND the
    # dense comparison the acceptance criterion records actually
    # dominates (dense F at K=5000 >= 4x the sparse state)
    checks["memory_pinned_m_not_k"] = (
        sparse_bytes_ratio < 1.05
        and dense_bytes[5000] >= 4 * nbytes[5000]
    )
    record["memory"] = {
        "affiliation_state_bytes_sparse": {
            str(k): v for k, v in nbytes.items()
        },
        "sparse_bytes_ratio_k5000_over_k1000": round(sparse_bytes_ratio, 4),
        "affiliation_state_bytes_dense_f32": {
            str(k): v for k, v in dense_bytes.items()
        },
        "dense_over_sparse_at_k5000": round(
            dense_bytes[5000] / nbytes[5000], 2
        ),
    }

    record["checks"] = checks
    record["device"] = str(jax.devices()[0])
    record["jax"] = jax.__version__
    record["pass"] = all(checks.values())
    line = json.dumps(record)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    return 0 if record["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
