"""Sharded-serving-fleet gate (ISSUE 18): prove on CPU, multi-process,
that the fleet delivers its contract end to end:

  drill             2 shards x 2 replicas as REAL `cli serve --fleet`
                    subprocesses (port 0, hello-line discovery), a Zipf
                    query mix of >= 12000 queries through `cli route`,
                    zero errors, per-shard p99/QPS tables recorded
  rollout           a new fleet generation published MID-STREAM: the
                    watch loop loads it, the router flips fleet-wide
                    (rollouts >= 1) with ZERO dropped queries and ZERO
                    mixed-generation answers
  overload          a burst at 16x concurrency against max-queue-depth=2
                    replicas sheds fast (serve_shed > 0, no errors) with
                    BOUNDED p99 — overload degrades, never OOMs or hangs
  parity            routed answers are bit-identical to a single-process
                    `cli serve` on the same F (modulo the router
                    stripping the "cached" transport key)
  ledger            the route run's p99/QPS/shed-rate land in the perf
                    ledger with shards x replicas in the match key; a
                    same-mix re-run baselines against it and diffs PASS;
                    `cli report` renders the fleet line + per-shard table

Emits one JSON artifact (FLEET_r22.json); exit 0 iff every check passes.

    python scripts/fleet_gate.py [out.json]
"""

import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N = 360
K = 12
P_IN = 0.7
PASS_QUERIES = 1250         # per routed pass; repeats push past 12000
MIN_QUERIES = 12000
ZIPF_A = 1.3


def _zipf_rank(rng, n, size):
    out = rng.zipf(ZIPF_A, size=size * 2) - 1
    out = out[out < n]
    while out.size < size:
        more = rng.zipf(ZIPF_A, size=size) - 1
        out = np.concatenate([out, more[more < n]])
    return out[:size]


def _cli(*argv, env=None, check=True, timeout=600):
    p = subprocess.run(
        [sys.executable, "-m", "bigclam_tpu.cli", *argv],
        capture_output=True, text=True, env=env, timeout=timeout,
    )
    if check and p.returncode != 0:
        raise RuntimeError(
            f"cli {argv[0]} failed rc={p.returncode}\n"
            f"stdout: {p.stdout[-2000:]}\nstderr: {p.stderr[-2000:]}"
        )
    return p


def _last_json(text):
    return json.loads(text.strip().splitlines()[-1])


def _load_jsonl(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else None

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.graph.store import compile_graph_cache
    from bigclam_tpu.models import BigClamModel
    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.obs import ledger as L
    from bigclam_tpu.serve.snapshot import (
        publish_fleet_snapshot,
        publish_snapshot,
    )

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    workdir = tempfile.mkdtemp(prefix="fleet_gate_")
    checks = {}
    record = {"gate": "fleet", "n": N, "k": K, "p_in": P_IN}
    procs = []

    try:
        # ---- one fit, three publications (identical F everywhere) ----
        rng = np.random.default_rng(7)
        g, _ = sample_planted_graph(N, K, p_in=P_IN, rng=rng)
        etxt = os.path.join(workdir, "g.txt")
        with open(etxt, "w") as f:
            for u in range(g.num_nodes):
                for j in range(g.indptr[u], g.indptr[u + 1]):
                    v = int(g.indices[j])
                    if u < v:
                        f.write(f"{g.raw_ids[u]} {g.raw_ids[v]}\n")
        cache = os.path.join(workdir, "g.cache")
        store = compile_graph_cache(etxt, cache, num_shards=4)

        cfg = BigClamConfig(num_communities=K, max_iters=500)
        model = BigClamModel(g, cfg)
        t0 = time.perf_counter()
        res = model.fit(model.random_init())
        record["fit_s"] = round(time.perf_counter() - t0, 3)
        record["fit_llh"] = res.llh

        single_dir = os.path.join(workdir, "single")
        publish_snapshot(
            single_dir, step=1, F=res.F, raw_ids=g.raw_ids,
            num_edges=g.num_edges, cfg=cfg, meta={"llh": res.llh},
        )
        fleet_dir = os.path.join(workdir, "fleet")
        ranges = store.host_ranges(2)
        gen1, _ = publish_fleet_snapshot(
            fleet_dir, ranges, F=res.F, raw_ids=g.raw_ids,
            num_edges=g.num_edges, cfg=cfg, meta={"llh": res.llh},
        )
        record["gen1"] = gen1

        # ---- the fleet: 2 shards x 2 replicas, real subprocesses -----
        def launch(shard, extra=()):
            p = subprocess.Popen(
                [sys.executable, "-m", "bigclam_tpu.cli", "serve",
                 "--fleet", fleet_dir, "--fleet-shard", str(shard),
                 "--listen", "127.0.0.1:0", "--graph", cache,
                 "--latency-budget-ms", "1",
                 "--max-queue-depth", "4096",
                 "--watch-snapshots", "0.2", *extra],
                stdout=subprocess.PIPE, text=True, env=env,
            )
            procs.append(p)
            hello = json.loads(p.stdout.readline())
            return p, hello["listening"]

        eps = []
        for s in (0, 1):
            for _ in range(2):
                _, ep = launch(s)
                eps.append(ep)
        endpoints = ",".join(eps)
        record["endpoints"] = eps

        # ---- Zipf mix: 45% members_of, 45% communities_of, 10% suggest
        qrng = np.random.default_rng(11)
        n_m = int(PASS_QUERIES * 0.45)
        n_c = int(PASS_QUERIES * 0.45)
        n_s = PASS_QUERIES - n_m - n_c
        queries = (
            [{"family": "members_of", "c": int(r)}
             for r in _zipf_rank(qrng, K, n_m)]
            + [{"family": "communities_of", "u": int(g.raw_ids[int(r)])}
               for r in _zipf_rank(qrng, N, n_c)]
            + [{"family": "suggest_for", "u": int(g.raw_ids[int(r)])}
               for r in _zipf_rank(qrng, N, n_s)]
        )
        qrng.shuffle(queries)
        qfile = os.path.join(workdir, "q.jsonl")
        with open(qfile, "w") as f:
            for q in queries:
                f.write(json.dumps(q) + "\n")

        # timing pass: sizes the drill so the mid-stream publication
        # lands while the router is demonstrably still routing
        t0 = time.perf_counter()
        warm = _last_json(_cli(
            "route", "--fleet", fleet_dir, "--endpoints", endpoints,
            "--queries", qfile, "--quiet", env=env,
        ).stdout)
        pass_wall = max(time.perf_counter() - t0 - 1.0, 0.5)
        if warm["serve_errors"]:
            raise RuntimeError(f"warm pass errored: {warm}")
        repeat = max(
            -(-MIN_QUERIES // PASS_QUERIES),       # >= 12000 queries
            int(np.ceil(12.0 / pass_wall)),        # >= ~12 s of routing
        )
        total = repeat * PASS_QUERIES
        record["drill"] = {"repeat": repeat, "queries": total,
                           "pass_wall_s": round(pass_wall, 2)}

        # ---- the drill + mid-stream rollout --------------------------
        ledger_path = os.path.join(workdir, "ledger.jsonl")
        telem = os.path.join(workdir, "telem")
        answers = os.path.join(workdir, "fleet_answers.jsonl")
        rt = subprocess.Popen(
            [sys.executable, "-m", "bigclam_tpu.cli", "route",
             "--fleet", fleet_dir, "--endpoints", endpoints,
             "--queries", qfile, "--repeat", str(repeat),
             "--health-interval-s", "0.2", "--results", answers,
             "--telemetry-dir", telem, "--perf-ledger", ledger_path,
             "--quiet"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        # publish generation 2 (same F — parity must survive the flip)
        # once the drill is clearly mid-stream
        time.sleep(max(2.0, pass_wall * max(repeat, 1) * 0.2))
        gen2, _ = publish_fleet_snapshot(
            fleet_dir, ranges, F=res.F, raw_ids=g.raw_ids,
            num_edges=g.num_edges, cfg=cfg, meta={"llh": res.llh},
        )
        record["gen2"] = gen2
        out, err = rt.communicate(timeout=900)
        if rt.returncode != 0:
            raise RuntimeError(
                f"route drill rc={rt.returncode}\n{out[-2000:]}\n"
                f"{err[-2000:]}"
            )
        stats = _last_json(out)
        shard_stats = stats.get("serve_shard_stats") or {}
        record["drill"].update({
            "p50_ms": round(stats["serve_p50_s"] * 1e3, 3),
            "p99_ms": round(stats["serve_p99_s"] * 1e3, 3),
            "qps": round(stats["serve_qps"], 1),
            "errors": stats["serve_errors"],
            "shed": stats["serve_shed"],
            "mix": stats["serve_mix"],
            "rollouts": stats["rollouts"],
            "mixed_generation": stats["mixed_generation"],
            "serving_generation": stats["serving_generation"],
            "shards": shard_stats,
        })
        checks["drill_12000_queries_zero_drops"] = (
            stats["serve_queries"] == total >= MIN_QUERIES
            and stats["serve_errors"] == 0
            and stats["serve_shed"] == 0
        )
        checks["drill_fleet_geometry"] = (
            stats["serve_shards"] == 2 and stats["serve_replicas"] == 2
        )
        checks["drill_per_shard_p99_recorded"] = (
            sorted(shard_stats) == ["0", "1"]
            and all(
                st["p99_s"] is not None and st["qps"] and st["queries"]
                for st in shard_stats.values()
            )
        )
        checks["rollout_flipped_fleet_wide"] = (
            stats["rollouts"] >= 1
            and stats["serving_generation"] == gen2
        )
        checks["rollout_zero_mixed_generation"] = (
            stats["mixed_generation"] == 0
        )

        # ---- parity vs single-process `cli serve` --------------------
        single_answers = os.path.join(workdir, "single_answers.jsonl")
        _cli(
            "serve", "--snapshots", single_dir, "--graph", cache,
            "--queries", qfile, "--results", single_answers, "--quiet",
            env=env,
        )
        a = _load_jsonl(answers)
        b = [
            {k: v for k, v in r.items() if k != "cached"}
            for r in _load_jsonl(single_answers)
        ]
        mism = sum(1 for x, y in zip(a, b) if x != y)
        record["parity"] = {"compared": len(a), "mismatches": mism}
        checks["parity_bit_identical"] = (
            len(a) == len(b) == PASS_QUERIES and mism == 0
        )

        # ---- ledger: same-mix re-run baselines + diffs PASS ----------
        rerun = _last_json(_cli(
            "route", "--fleet", fleet_dir, "--endpoints", endpoints,
            "--queries", qfile, "--repeat", "2",
            "--telemetry-dir", os.path.join(workdir, "telem2"),
            "--perf-ledger", ledger_path, "--quiet", env=env,
        ).stdout)
        checks["ledger_rerun_clean"] = rerun["serve_errors"] == 0
        led = L.PerfLedger(ledger_path)
        recs = led.load()
        route_recs = [r for r in recs if r.get("entry") == "route"]
        checks["ledger_two_route_records"] = len(route_recs) == 2
        if len(route_recs) == 2:
            checks["ledger_fleet_geometry_in_record"] = all(
                r.get("serve_shards") == 2
                and r.get("serve_replicas") == 2
                for r in route_recs
            )
            base = led.baseline_for(route_recs[1], recs)
            checks["ledger_baseline_found"] = (
                base is not None
                and base.get("run") == route_recs[0].get("run")
            )
            diff = L.diff_records(route_recs[0], route_recs[1],
                                  tolerance=5.0)
            # tolerance 5.0 pins the WIRING (fleet p99/QPS/shed are
            # verdicted, a same-mix re-run passes); band arithmetic is
            # unit-tested in tests/test_fleet.py
            checks["ledger_rerun_diff_passes"] = not diff["regression"]
            checks["ledger_p99_verdicted"] = any(
                c["metric"] == "serve_p99_s" and c.get("verdicted")
                for c in diff["checks"] if not c.get("skipped")
            )

        # ---- `cli report` renders the fleet + per-shard table --------
        rep = _cli("report", telem, env=env).stdout
        checks["report_fleet_line"] = "fleet: 2 shard(s)" in rep
        checks["report_per_shard_table"] = (
            "shard" in rep and "p99 ms" in rep
        )

        # ---- overload burst: shed fast, bounded p99 ------------------
        burst_eps = []
        for s in (0, 1):
            _, ep = launch(s, extra=(
                "--max-queue-depth", "2", "--latency-budget-ms", "50",
            ))
            burst_eps.append(ep)
        burst_q = os.path.join(workdir, "burst.jsonl")
        with open(burst_q, "w") as f:
            for r in _zipf_rank(qrng, N, 600):
                f.write(json.dumps(
                    {"family": "communities_of",
                     "u": int(g.raw_ids[int(r)])}) + "\n")
        burst = _last_json(_cli(
            "route", "--fleet", fleet_dir,
            "--endpoints", ",".join(burst_eps),
            "--queries", burst_q, "--max-workers", "32", "--quiet",
            env=env,
        ).stdout)
        record["overload"] = {
            "queries": burst["serve_queries"],
            "shed": burst["serve_shed"],
            "shed_rate": burst["serve_shed_rate"],
            "errors": burst["serve_errors"],
            "p99_ms": round(burst["serve_p99_s"] * 1e3, 3),
        }
        checks["overload_sheds"] = burst["serve_shed"] > 0
        checks["overload_no_errors"] = burst["serve_errors"] == 0
        # bounded: shed answers return ~instantly and admitted ones ride
        # one 50 ms batch window — nothing waits an unbounded queue
        checks["overload_p99_bounded"] = burst["serve_p99_s"] < 2.0
        _cli("route", "--fleet", fleet_dir,
             "--endpoints", ",".join(burst_eps), "--stop", env=env)

        # ---- teardown: route --stop, every replica exits 0 -----------
        _cli("route", "--fleet", fleet_dir, "--endpoints", endpoints,
             "--stop", env=env)
        codes = [p.wait(timeout=30) for p in procs]
        record["replica_exit_codes"] = codes
        checks["teardown_clean_exits"] = all(c == 0 for c in codes)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    # ---- verdict ----------------------------------------------------
    record["checks"] = checks
    record["pass"] = all(checks.values())
    line = json.dumps(record)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    return 0 if record["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
