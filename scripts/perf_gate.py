"""Perf-ledger regression gate (ISSUE 6 satellite): prove the ledger +
diff machinery end to end on CPU, fast enough for CI.

Three runs of the SAME tiny fit config, all appending to one fresh ledger
(BIGCLAM_PERF_LEDGER is set for the whole gate, so the records flow
through the real RunTelemetry.finalize auto-append path):

  A  baseline           — recorded; `cli perf diff` correctly refuses
                          (no earlier matched record to compare against)
  B  identical re-run   — `cli perf diff` matches it against A and PASSES
                          within the noise bands (exit 0)
  C  injected slowdown  — the existing fault-injection harness
                          (resilience.faults) fires a `delay` at site
                          "fit.step" on EVERY iteration, multiplying the
                          per-step time by >> the noise band; `cli perf
                          diff` flags the regression with a NONZERO exit

plus record-schema validation and a baseline-matching check (a run with a
different K must NOT match A/B). Emits one JSON artifact line
(PERF_r10.json); exit 0 iff every check passes.

    python scripts/perf_gate.py [out.json]
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else None

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from bigclam_tpu.cli import main as cli_main
    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.models import BigClamModel
    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.obs import RunTelemetry, install, uninstall
    from bigclam_tpu.obs import ledger as L
    from bigclam_tpu.resilience import FaultPlan, install_plan
    from bigclam_tpu.utils.metrics import MetricsLogger
    from bigclam_tpu.utils.profiling import StageProfile

    g, _ = sample_planted_graph(240, 4, p_in=0.3, rng=np.random.default_rng(0))
    iters = 30
    cfg = BigClamConfig(
        num_communities=4, dtype="float64", max_iters=iters, conv_tol=0.0
    )
    F0 = np.random.default_rng(1).uniform(0.1, 1.0, size=(g.num_nodes, 4))

    root = tempfile.mkdtemp(prefix="perf_gate_")
    ledger_path = os.path.join(root, "ledger.jsonl")
    os.environ["BIGCLAM_PERF_LEDGER"] = ledger_path
    checks = {}

    def one_run(tag, delay_s=None, k=4, f0=None):
        tel = install(
            RunTelemetry(
                os.path.join(root, tag), entry="fit", quiet=True
            )
        )
        try:
            if delay_s is not None:
                install_plan(
                    FaultPlan(
                        [
                            {"kind": "delay", "site": "fit.step",
                             "at": i, "seconds": delay_s}
                            for i in range(iters + 1)
                        ]
                    )
                )
            prof = StageProfile()
            c = cfg.replace(num_communities=k)
            f = F0 if f0 is None else f0
            with prof.stage("model_build"):
                model = BigClamModel(g, c)
            with prof.stage("fit"), MetricsLogger(None, echo=False) as ml:
                res = model.fit(
                    f,
                    callback=ml.step_callback(
                        g.num_directed_edges, num_nodes=g.num_nodes
                    ),
                )
            tel.set_final({"llh": res.llh})
            tel.finalize()
        finally:
            install_plan(None)
            uninstall(tel)

    def diff_rc():
        try:
            return cli_main(["perf", "diff", "--ledger", ledger_path])
        except SystemExit as e:      # argparse never exits here, but safe
            return int(e.code or 0)

    # A: baseline — diff must refuse (nothing matched before it)
    one_run("a")
    checks["no_baseline_refused"] = diff_rc() == 1

    # B: identical config — must PASS within noise bands
    one_run("b")
    rc_b = diff_rc()
    checks["identical_rerun_passes"] = rc_b == 0

    # record schema + baseline matching sanity
    recs = L.PerfLedger(ledger_path).load()
    checks["records_schema_valid"] = all(
        L.validate_record(r) == [] for r in recs
    )
    checks["baseline_matched_pair"] = (
        len(recs) == 2
        and L.match_key(recs[0]) == L.match_key(recs[1])
        and recs[0].get("step_p50") is not None
    )

    # different K must NOT match the A/B baseline chain
    F3 = np.random.default_rng(2).uniform(0.1, 1.0, size=(g.num_nodes, 3))
    one_run("k3", k=3, f0=F3)
    checks["different_config_refused"] = diff_rc() == 1

    # C: synthetic slowdown via the resilience delay site — the injected
    # per-step delay is sized from the MEASURED baseline p50 so the gate
    # is robust on any host: >= 4x p50 clears every noise band
    base_p50 = recs[0].get("step_p50") or 0.005
    delay = max(4.0 * base_p50, 0.02)
    one_run("c", delay_s=delay)
    rc_c = diff_rc()
    checks["injected_slowdown_flagged_nonzero"] = rc_c == 2
    recs = L.PerfLedger(ledger_path).load()
    slow = recs[-1]
    checks["slowdown_visible_in_record"] = (
        slow.get("step_p50", 0) > (recs[0].get("step_p50") or 0) * 2
    )

    record = {
        "gate": "perf-ledger",
        "config": f"planted AGM N={g.num_nodes} K=4 "
                  f"2E={g.num_directed_edges}, max_iters={iters}",
        "ledger_records": len(recs),
        "baseline_step_p50": recs[0].get("step_p50"),
        "slowdown_step_p50": slow.get("step_p50"),
        "injected_delay_s": round(delay, 4),
        "diff_rc": {"no_baseline": 1, "identical": rc_b, "slow": rc_c},
        "checks": checks,
        "device": str(jax.devices()[0]),
        "jax": jax.__version__,
        "pass": all(checks.values()),
    }
    line = json.dumps(record)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    return 0 if record["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
