"""Fused-2D gate (ISSUE 17): prove, on CPU fakes, that the fused Pallas
superstep engages on the 2D edge-block path without changing the math,
and that the closure grad exchange actually shrinks the grad wire.

Check groups, the ISSUE 17 acceptance criteria verbatim:

  engage        --partition 2d + CSR kernels engages kernel_path
                csr_fused_2d on the in-memory AND store-native
                trainers, csr_fused_2d_kb on the K-blocked layout —
                reported, not silently fallen back from
  identity      the fused 2D trajectory at C=1 is bit-identical to the
                1D FUSED trainer (same llh scalar, array-equal F) for
                both the flat and K-blocked kernels — the closure
                buffer feeding the DMA descriptors is a relabeling of
                the same gathered rows; (2,2) stays inside the 5e-3
                LLH band and its closure-grad fit equals its dense-grad
                fit bit-exactly
  grad curve    modeled closure-grad bytes strictly below the dense
                psum_grad they replace at p in {4,8} (grids (2,2) and
                (2,4)) on a uniform sparse toy, with the touched cap
                below rows-per-block — and modeled within 2% of the
                live remeasure on the closure config
  overflow      an explicit closure_grad_cap below the true pair
                maximum falls back to the dense psum PER STEP inside
                the same compiled executable (counters latch, exactly
                one compile) and the trajectory equals the dense run
                bit-exactly
  memory        the fused 2D closure config reconciles modeled-vs-live
                HBM at drift 0 on the CPU fake
  ledger        fused-vs-XLA are SEPARATE baselines: a same-config
                re-run baselines clean (exit 0), the same record
                restamped kernel_path=xla_2d finds NO baseline
                (exit 1), and restamping grad_exchange=dense refuses
                the same way
  preflight     the Friendster-K=25K dense 2D verdict prices the
                COMBINED config (workload names kernel_path
                csr_fused_2d + grad_exchange closure, note says so),
                and the round-20 sparse 2D flip keeps exit 0

    python scripts/fused2d_gate.py [FUSED2D_r21.json]

Exit 0 iff every check passes.
"""

import contextlib
import io
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else None

    import jax

    jax.config.update("jax_platforms", "cpu")
    from bigclam_tpu.utils.dist import request_cpu_devices

    request_cpu_devices(8)

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.graph.ingest import graph_from_edges
    from bigclam_tpu.graph.store import compile_graph_cache
    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.obs import RunTelemetry, install, uninstall
    from bigclam_tpu.obs import ledger as L
    from bigclam_tpu.obs.report import load_events
    from bigclam_tpu.parallel import (
        ShardedBigClamModel,
        StoreTwoDShardedBigClamModel,
        TwoDShardedBigClamModel,
        make_mesh,
        make_mesh_2d,
    )
    from bigclam_tpu.utils.profiling import StageProfile

    checks = {}
    detail = {}
    devs = jax.devices()
    K = 8
    # interpret-mode Pallas on the CPU fake; tile shapes sized to the
    # 240-node planted toy (n_blk=60 at p=4 -> block_b=30 divides it on
    # the (4,1) and (2,2) grids)
    FUSED = dict(use_pallas_csr=True, pallas_interpret=True,
                 csr_block_b=30, csr_tile_t=64)

    def cfg(**kw):
        d = dict(num_communities=K, max_iters=6, conv_tol=0.0,
                 health_every=2, seed=0)
        d.update(kw)
        return BigClamConfig(**d)

    rng = np.random.default_rng(0)
    g, _ = sample_planted_graph(240, 4, p_in=0.3, rng=rng)
    F0 = np.abs(rng.standard_normal((g.num_nodes, K))).astype(np.float32)

    # --- 1. engagement + C=1 bit-identity vs the 1D FUSED trainer -----
    m1 = ShardedBigClamModel(g, cfg(**FUSED), make_mesh((4, 1), devs[:4]))
    checks["engage_1d_fused_anchor"] = m1.engaged_path == "csr_fused"
    m2 = TwoDShardedBigClamModel(
        g, cfg(partition="2d", replica_cols=1, **FUSED),
        make_mesh_2d((4, 1), devs[:4]),
    )
    checks["engage_2d_fused"] = m2.engaged_path == "csr_fused_2d"

    work = tempfile.mkdtemp(prefix="fused2d_gate_")
    tdir = os.path.join(work, "fit2d")
    tel = install(RunTelemetry(tdir, entry="fit", quiet=True))
    try:
        with StageProfile().stage("fit"):
            r2 = m2.fit(F0.copy())
        tel.set_final({
            "llh": r2.llh, "iters": r2.num_iters, "n": g.num_nodes,
            "edges": g.num_edges, "k": K, "mesh": "4x1",
            "partition": "2d", "kernel_path": m2.engaged_path,
            "grad_exchange": m2.grad_exchange,
        })
        rep = tel.finalize()
    finally:
        uninstall(tel)
    r1 = m1.fit(F0.copy())
    checks["identity_c1_llh_equal"] = r1.llh == r2.llh
    checks["identity_c1_F_array_equal"] = bool(
        np.array_equal(np.asarray(r1.F), np.asarray(r2.F))
    )

    m1kb = ShardedBigClamModel(
        g, cfg(csr_k_block=4, **FUSED), make_mesh((4, 1), devs[:4])
    )
    m2kb = TwoDShardedBigClamModel(
        g, cfg(partition="2d", replica_cols=1, csr_k_block=4, **FUSED),
        make_mesh_2d((4, 1), devs[:4]),
    )
    checks["engage_2d_fused_kb"] = m2kb.engaged_path == "csr_fused_2d_kb"
    r1kb, r2kb = m1kb.fit(F0.copy()), m2kb.fit(F0.copy())
    checks["identity_c1_kb_llh_equal"] = r1kb.llh == r2kb.llh
    checks["identity_c1_kb_F_array_equal"] = bool(
        np.array_equal(np.asarray(r1kb.F), np.asarray(r2kb.F))
    )

    # store-native engagement + equality with the in-memory fused run
    txt = os.path.join(work, "g.txt")
    src, dst = np.asarray(g.src), np.asarray(g.dst)
    with open(txt, "w") as f:
        for s, d in zip(src.tolist(), dst.tolist()):
            if s < d:
                f.write(f"{s}\t{d}\n")
    store = compile_graph_cache(txt, os.path.join(work, "cache"),
                                num_shards=4)
    mst = StoreTwoDShardedBigClamModel(
        store, cfg(partition="2d", replica_cols=1, **FUSED),
        make_mesh_2d((4, 1), devs[:4]),
    )
    checks["engage_2d_fused_store"] = mst.engaged_path == "csr_fused_2d"
    rst = mst.fit(F0.copy())
    checks["identity_store_equals_in_memory"] = (
        rst.llh == r2.llh
        and bool(np.array_equal(np.asarray(rst.F), np.asarray(r2.F)))
    )
    detail["identity"] = {
        "llh_1d_fused": r1.llh, "llh_2d_fused": r2.llh,
        "llh_1d_fused_kb": r1kb.llh, "llh_2d_fused_kb": r2kb.llh,
        "llh_2d_fused_store": rst.llh,
    }

    # --- 2. (2,2): LLH band + closure grad == dense grad bit-exactly --
    m22 = {}
    fit22 = {}
    for gx in ("closure", "dense"):
        m22[gx] = TwoDShardedBigClamModel(
            g, cfg(partition="2d", replica_cols=2, grad_exchange=gx,
                   **FUSED),
            make_mesh_2d((2, 2), devs[:4]),
        )
        fit22[gx] = m22[gx].fit(F0.copy())
    checks["engage_2x2_fused"] = (
        m22["closure"].engaged_path == "csr_fused_2d"
        and m22["closure"].grad_exchange == "closure"
    )
    checks["identity_2x2_closure_equals_dense"] = (
        fit22["closure"].llh == fit22["dense"].llh
        and bool(np.array_equal(np.asarray(fit22["closure"].F),
                                np.asarray(fit22["dense"].F)))
    )
    rel_llh = abs(fit22["closure"].llh - r1.llh) / max(abs(r1.llh), 1.0)
    checks["llh_band_2x2"] = rel_llh < 5e-3
    detail["identity"]["rel_llh_2x2_vs_1d"] = rel_llh

    # --- 3. grad curve on a uniform sparse toy at p in {4,8} ----------
    # same regime argument as the round-20 gate: closure undercuts
    # dense iff the touched cap < rows-per-block, which needs edges
    # spread uniformly over block pairs (a planted toy's cliques touch
    # whole blocks — the model honestly prices those at >= dense, see
    # tests/test_fused2d.py's honest-curve test)
    n_toy, m_toy = 1024, 2048
    pairs = rng.integers(0, n_toy, size=(4 * m_toy, 2))
    pairs = pairs[pairs[:, 0] != pairs[:, 1]]
    pairs = np.unique(np.sort(pairs, axis=1), axis=0)
    gt = graph_from_edges(pairs[rng.permutation(len(pairs))[:m_toy]],
                          num_nodes=n_toy)
    Ft = np.abs(rng.standard_normal((gt.num_nodes, K))).astype(np.float32)
    curve = {}
    for rows, cols in ((2, 2), (2, 4)):
        p = rows * cols
        mc = TwoDShardedBigClamModel(
            gt, cfg(partition="2d", replica_cols=cols,
                    grad_exchange="closure"),
            make_mesh_2d((rows, cols), devs[:p]),
        )
        md = TwoDShardedBigClamModel(
            gt, cfg(partition="2d", replica_cols=cols,
                    grad_exchange="dense"),
            make_mesh_2d((rows, cols), devs[:p]),
        )
        sc, sd = mc.comms.site_bytes(), md.comms.site_bytes()
        closure_b = (sc["twod/alltoall_grad_closure"]
                     + sc["twod/pmax_grad_count"]
                     + sc["twod/pmax_grad_count_rows"])
        dense_b = sd["twod/psum_grad"]
        n_blk = mc.n_pad // p
        curve[f"{rows}x{cols}"] = {
            "grad_bytes_closure": round(closure_b, 1),
            "grad_bytes_dense": round(dense_b, 1),
            "ratio": round(closure_b / dense_b, 4),
            "grad_cap": int(mc._grad_cap),
            "rows_per_block": int(n_blk),
        }
        checks[f"grad_p{p}_closure_below_dense"] = closure_b < dense_b
        checks[f"grad_p{p}_cap_below_block"] = mc._grad_cap < n_blk
        if (rows, cols) == (2, 2):
            st = mc.init_state(Ft)
            st = mc._step(st)
            modeled = mc.comms.bytes_per_step()
            measured = mc.comms_measured(st).bytes_per_step()
            rel = abs(measured - modeled) / max(modeled, 1e-9)
            curve["2x2"]["model_vs_measured_rel"] = round(rel, 6)
            checks["grad_model_vs_measured_2pct"] = rel <= 0.02
    detail["grad_curve"] = curve

    # --- 4. overflow: per-step dense fallback, one compile ------------
    mof = TwoDShardedBigClamModel(
        g, cfg(partition="2d", replica_cols=2, grad_exchange="closure",
               closure_grad_cap=1, **FUSED),
        make_mesh_2d((2, 2), devs[:4]),
    )
    rof = mof.fit(F0.copy())
    stof = mof.init_state(F0)
    stof = mof._step(stof)
    ids, fell_back = mof.last_comm(stof)
    checks["overflow_counter_latches"] = fell_back and ids > 1
    checks["overflow_equals_dense_fit"] = (
        rof.llh == fit22["dense"].llh
        and bool(np.array_equal(np.asarray(rof.F),
                                np.asarray(fit22["dense"].F)))
    )
    detail["overflow"] = {"cap": 1, "true_ids": int(ids),
                          "pair_max": int(mof._grad_pair_max)}

    # --- 5. memory: fused closure config reconciles at drift 0 --------
    st22 = m22["closure"].init_state(F0)
    st22 = m22["closure"]._step(st22)
    rec = m22["closure"].memory_reconcile(st22)
    checks["memory_drift_zero"] = rec["ok"] and rec["drift_frac"] == 0.0
    detail["memory"] = {
        "modeled_bytes": rec["modeled_bytes"],
        "measured_bytes": rec["measured_bytes"],
        "drift_frac": rec["drift_frac"],
    }

    # --- 6. perf ledger: fused-vs-XLA are separate baselines ----------
    from bigclam_tpu.cli import main as cli_main

    events = load_events(tdir) or []
    secs = [e["sec_per_iter"] for e in events
            if e.get("kind") == "step"
            and isinstance(e.get("sec_per_iter"), (int, float))]
    base_rec = L.build_record(rep, secs or [0.01] * 6)
    checks["record_carries_kernel_path"] = (
        base_rec.get("kernel_path") == "csr_fused_2d"
    )
    ledger_path = os.path.join(work, "ledger.jsonl")
    led = L.PerfLedger(ledger_path)
    led.append(base_rec)
    led.append(dict(base_rec, run="rerun", ts=base_rec["ts"] + 1))
    rc_same = cli_main(["perf", "diff", "--ledger", ledger_path])
    checks["perf_diff_same_config_baselines"] = rc_same == 0
    # the SAME record restamped as the XLA path: the A/B twin must
    # find no fused baseline to diff against
    led.append(dict(base_rec, run="as-xla", ts=base_rec["ts"] + 2,
                    kernel_path="xla_2d"))
    rc_path = cli_main(["perf", "diff", "--ledger", ledger_path])
    checks["perf_diff_kernel_path_refusal"] = rc_path == 1
    # ... and the same for the grad exchange mode (the C=1 base run is
    # grad_exchange=dense — restamp it as a closure run)
    led.append(dict(base_rec, run="as-closure-grad",
                    ts=base_rec["ts"] + 3, grad_exchange="closure"))
    rc_gx = cli_main(["perf", "diff", "--ledger", ledger_path])
    checks["perf_diff_grad_exchange_refusal"] = rc_gx == 1
    detail["perf_diff"] = {"same_rc": rc_same, "path_rc": rc_path,
                           "grad_rc": rc_gx}

    # --- 7. preflight: Friendster dense-2D names the combined config --
    fake = os.path.join(work, "edges.txt")
    with open(fake, "w") as f:
        f.write("0 1\n")
    base_args = [
        "preflight", "--graph", fake,
        "--nodes", "65608366", "--edges", "1806067135",
        "--k", "25000", "--device-kind", "v5e",
        "--mesh", "64,1", "--json",
    ]

    def run_preflight(extra):
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli_main(base_args + extra)
        return rc, json.loads(buf.getvalue())

    rc_d2, p_d2 = run_preflight(["--partition", "2d",
                                 "--replica-cols", "8"])
    w2 = p_d2.get("workload", {})
    checks["preflight_dense2d_names_fused"] = (
        w2.get("kernel_path") == "csr_fused_2d"
    )
    checks["preflight_dense2d_names_closure_grad"] = (
        w2.get("grad_exchange") == "closure"
    )
    checks["preflight_dense2d_combined_note"] = any(
        "csr_fused_2d" in n and "grad_exchange" in n
        for n in p_d2.get("notes", [])
    )
    # the round-20 flip must survive: sparse m=48 on the 2d grid fits
    rc_s2, p_s2 = run_preflight([
        "--representation", "sparse", "--sparse-m", "48",
        "--partition", "2d", "--replica-cols", "8",
    ])
    checks["preflight_sparse2d_still_fits"] = rc_s2 == 0 and p_s2["fits"]
    detail["preflight"] = {
        "dense2d_rc": rc_d2,
        "dense2d_kernel_path": w2.get("kernel_path"),
        "dense2d_grad_exchange": w2.get("grad_exchange"),
        "sparse2d_rc": rc_s2,
    }

    ok = all(checks.values())
    artifact = {
        "gate": "fused2d_r21",
        "created_unix": round(time.time(), 1),
        "pass": ok,
        "checks": checks,
        "detail": detail,
        "device": str(jax.devices()[0]),
        "jax": jax.__version__,
        "note": (
            "fused Pallas superstep engages on the 2D edge-block path "
            "(csr_fused_2d / _kb / store-native) with C=1 bit-identity "
            "to the 1D fused trainer and (2,2) inside the LLH band; "
            "closure grad exchange strictly under the dense psum at "
            "p in {4,8} on a degree-4 sparse toy with modeled bytes "
            "within 2% of live buffers; cap overflow degrades to the "
            "dense psum per step inside one executable and matches the "
            "dense trajectory bit-exactly; memory reconciles at drift "
            "0; kernel_path and grad_exchange are both perf-ledger "
            "baseline keys; cli preflight prices Friendster-K25K dense "
            "2D as the combined fused+closure-grad config and keeps "
            "the round-20 sparse flip."
        ),
    }
    line = json.dumps(artifact, sort_keys=True)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    if not ok:
        bad = sorted(k for k, v in checks.items() if not v)
        print(f"FAILED checks: {bad}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
