"""Store-native compute gate (ISSUE 9) -> STORE_NATIVE_r13.json.

Proves the four tentpole claims on the CPU fake (8 virtual devices):

1. trajectory_identity — store-backed fits equal the in-memory trainers
   BIT-FOR-BIT across the schedule matrix {sharded, ring} x {XLA float64,
   blocked-CSR interpret float32 (use_pallas_csr=True on the store path —
   the lifted refusal), ring K-blocked}.
2. files_read_isolation — with two fake hosts (load_shard_range halves),
   tile builds, ring bucket builds, and baked-seed loads touch ONLY that
   host's shard files, and the cross-host-padded layouts concatenate to
   the host-global builders' arrays exactly.
3. baked_seeds — ingest-baked conductance scores == the streamed scorer
   (bit-identical exact path; capped estimator within float tolerance and
   rank-identical).
4. rss_budget — a jax-free subprocess loading ONE host's half of a
   4M-edge cache and building its tiles + ring buckets stays inside an
   EXPLICIT O(shard) budget (budget = 4 x predicted half-structure bytes
   + 160 MiB runtime slack), with the host-global equivalent recorded for
   contrast.

Run:  JAX_PLATFORMS=cpu python scripts/store_native_gate.py
"""

import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from bigclam_tpu.utils.dist import request_cpu_devices  # noqa: E402

request_cpu_devices(8)

import numpy as np  # noqa: E402

ARTIFACT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "STORE_NATIVE_r13.json",
)

_RSS_CHILD = r"""
import json, os, sys
sys.path.insert(0, sys.argv[1])
import numpy as np
from bigclam_tpu.graph.store import GraphStore
from bigclam_tpu.ops import csr_tiles as ct
from bigclam_tpu.parallel.ring import ring_bucket_local_max, ring_shard_edges_local
from bigclam_tpu.utils.profiling import current_rss_bytes
from bigclam_tpu.config import BigClamConfig

cache, mode = sys.argv[2], sys.argv[3]
store = GraphStore.open(cache)
dp = store.num_shards
n_pad = dp * store.rows_per_shard
block_b, tile_t = int(sys.argv[4]), int(sys.argv[5])
cfg = BigClamConfig()
base = current_rss_bytes()
if mode == "half":
    hs = store.load_shard_range(0, dp // 2)
    parts = ct.local_block_tile_parts(hs, dp, n_pad, block_b, tile_t)
    sbt = ct.stack_block_tile_parts(parts, max(p.n_tiles for p in parts))
    mx = ring_bucket_local_max(hs, dp, n_pad)
    buckets = ring_shard_edges_local(hs, cfg, dp, n_pad, np.float32,
                                     chunk_bound=1 << 16, max_count=mx)
    phi = store.load_seed_scores(0, dp // 2)
    structure = (hs.indices.nbytes + hs.indptr.nbytes
                 + sbt.src_local.nbytes + sbt.dst.nbytes + sbt.mask.nbytes
                 + buckets.src.nbytes + buckets.dst.nbytes + buckets.mask.nbytes
                 + phi.phi.nbytes)
    files = len(hs.files_read)
else:
    g = store.load_graph(mmap=False)
    sbt = ct.shard_block_tiles(g, dp, n_pad, block_b, tile_t)
    from bigclam_tpu.parallel.ring import ring_shard_edges
    buckets = ring_shard_edges(g, cfg, dp, n_pad, np.float32,
                               chunk_bound=1 << 16)
    structure = (g.indices.nbytes + g.indptr.nbytes
                 + sbt.src_local.nbytes + sbt.dst.nbytes + sbt.mask.nbytes
                 + buckets.src.nbytes + buckets.dst.nbytes + buckets.mask.nbytes)
    files = -1
print(json.dumps({
    "rss_delta_bytes": current_rss_bytes() - base,
    "structure_bytes": int(structure),
    "files_read": files,
}))
"""


def build_cache(tmp, n, m_und, shards, name, seed_cap=None):
    from bigclam_tpu.graph.store import compile_graph_cache

    rng = np.random.default_rng(42)
    u = rng.integers(0, n, m_und, dtype=np.int64)
    v = rng.integers(0, n, m_und, dtype=np.int64)
    keep = u != v
    text = os.path.join(tmp, f"{name}.txt")
    np.savetxt(text, np.stack([u[keep], v[keep]], 1), fmt="%d")
    cache = os.path.join(tmp, f"{name}.cache")
    store = compile_graph_cache(
        text, cache, num_shards=shards, chunk_bytes=4 << 20,
        seed_cap=seed_cap,
    )
    return text, store


def trajectory_identity(tmp):
    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.graph.ingest import build_graph
    from bigclam_tpu.parallel import (
        RingBigClamModel,
        ShardedBigClamModel,
        StoreRingBigClamModel,
        StoreShardedBigClamModel,
        make_mesh,
    )

    text, store = build_cache(tmp, 480, 4000, 4, "traj")
    g = build_graph(text)
    F0 = np.random.default_rng(1).uniform(0.05, 0.9, size=(g.num_nodes, 4))
    mesh = make_mesh((4, 1), jax.devices()[:4])
    rows = store.rows_per_shard
    assert rows % 4 == 0, rows
    xla = BigClamConfig(num_communities=4, dtype="float64", max_iters=5,
                        conv_tol=0.0, use_pallas_csr=False)
    csr = BigClamConfig(num_communities=4, dtype="float32", max_iters=4,
                        conv_tol=0.0, use_pallas_csr=True,
                        pallas_interpret=True, csr_block_b=rows // 4,
                        csr_tile_t=32)
    cases = []
    matrix = [
        ("sharded_xla", ShardedBigClamModel, StoreShardedBigClamModel,
         xla, {}),
        ("ring_xla", RingBigClamModel, StoreRingBigClamModel, xla,
         {"balance": False}),
        ("sharded_csr_interpret", ShardedBigClamModel,
         StoreShardedBigClamModel, csr, {}),
        ("ring_csr_interpret", RingBigClamModel, StoreRingBigClamModel,
         csr, {"balance": False}),
        ("ring_csr_kblocked", RingBigClamModel, StoreRingBigClamModel,
         csr.replace(csr_k_block=2), {"balance": False}),
    ]
    for name, mem_cls, store_cls, cfg, kw in matrix:
        t0 = time.time()
        mem = mem_cls(g, cfg, mesh, **kw)
        ref = mem.fit(F0)
        sm = store_cls(store, cfg, mesh)
        got = sm.fit(F0)
        bit_identical = (
            np.array_equal(got.F, ref.F)
            and got.llh_history == ref.llh_history
        )
        cases.append({
            "case": name,
            "engaged_path_in_memory": mem.engaged_path,
            "engaged_path_store": sm.engaged_path,
            "paths_agree": mem.engaged_path == sm.engaged_path,
            "bit_identical_trajectory": bool(bit_identical),
            "iters": ref.num_iters,
            "seconds": round(time.time() - t0, 2),
        })
    ok = all(
        c["bit_identical_trajectory"] and c["paths_agree"] for c in cases
    )
    return {"ok": ok, "cases": cases}


def files_read_isolation(tmp):
    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.graph.ingest import build_graph
    from bigclam_tpu.graph.store import GraphStore
    from bigclam_tpu.ops import csr_tiles as ct
    from bigclam_tpu.parallel.ring import (
        ring_shard_edges,
        ring_shard_edges_local,
        ring_bucket_imbalance,
    )

    text = os.path.join(tmp, "traj.txt")
    store = GraphStore.open(os.path.join(tmp, "traj.cache"))
    g = build_graph(text)
    dp = store.num_shards
    n_pad = dp * store.rows_per_shard
    block_b, tile_t = store.rows_per_shard // 4, 32
    cfg = BigClamConfig()
    ref_tiles = ct.shard_block_tiles(g, dp, n_pad, block_b, tile_t)
    ref_buckets = ring_shard_edges(g, cfg, dp, n_pad, np.float32,
                                   chunk_bound=1 << 14)
    mx = ring_bucket_imbalance(g, dp, n_pad)[0]
    checks = []
    for h in range(2):
        lo_s, hi_s = h * dp // 2, (h + 1) * dp // 2
        hs = store.load_shard_range(lo_s, hi_s)
        own = {
            os.path.basename(p)
            for s in hs.shard_ids for p in store.shard_files(s)
        }
        parts = ct.local_block_tile_parts(hs, dp, n_pad, block_b, tile_t)
        tiles = ct.stack_block_tile_parts(parts, ref_tiles.n_tiles)
        buckets = ring_shard_edges_local(hs, cfg, dp, n_pad, np.float32,
                                         chunk_bound=1 << 14, max_count=mx)
        phi = store.load_seed_scores(lo_s, hi_s)
        checks.append({
            "host": h,
            "shard_files_read_own_only": set(hs.files_read) == own,
            "phi_files_read_own_only": set(phi.files_read) == {
                f"shard_{s:05d}.phi.npy" for s in hs.shard_ids
            },
            "tiles_equal_host_global_rows": bool(
                np.array_equal(tiles.src_local,
                               ref_tiles.src_local[lo_s:hi_s])
                and np.array_equal(tiles.dst, ref_tiles.dst[lo_s:hi_s])
                and np.array_equal(tiles.mask, ref_tiles.mask[lo_s:hi_s])
            ),
            "buckets_equal_host_global_rows": bool(
                np.array_equal(buckets.src, ref_buckets.src[lo_s:hi_s])
                and np.array_equal(buckets.dst, ref_buckets.dst[lo_s:hi_s])
            ),
        })
    ok = all(all(v for k, v in c.items() if k != "host") for c in checks)
    return {"ok": ok, "hosts": checks}


def baked_seeds(tmp):
    from bigclam_tpu.graph.ingest import build_graph
    from bigclam_tpu.graph.store import GraphStore
    from bigclam_tpu.ops import seeding

    text = os.path.join(tmp, "traj.txt")
    store = GraphStore.open(os.path.join(tmp, "traj.cache"))
    g = build_graph(text)
    baked = store.load_seed_scores().phi
    streamed = seeding.conductance(g, backend="numpy")
    exact_identical = bool(np.array_equal(baked, streamed))

    cap = 12
    _, store_c = build_cache(tmp, 480, 4000, 4, "capped", seed_cap=cap)
    baked_c = store_c.load_seed_scores().phi
    streamed_c = seeding.conductance(
        g, backend="sampled", degree_cap=cap, rng=np.random.default_rng(0)
    )
    rel = float(
        np.max(
            np.abs(baked_c - streamed_c)
            / np.maximum(np.abs(streamed_c), 1e-12)
        )
    )
    rank_same = bool(
        np.array_equal(
            seeding.rank_seeds(g, baked_c), seeding.rank_seeds(g, streamed_c)
        )
    )
    return {
        "ok": exact_identical and rel < 1e-8 and rank_same,
        "exact_bit_identical": exact_identical,
        "capped_max_rel_diff": rel,
        "capped_rank_identical": rank_same,
    }


def rss_budget(tmp, repo):
    _, store = build_cache(tmp, 120_000, 4_000_000, 4, "big")
    rows = store.rows_per_shard
    # largest divisor of the shard rows <= 256 (store tiles keep shard
    # boundaries, so block_b must divide rows_per_shard)
    block_b = next(d for d in range(256, 0, -1) if rows % d == 0)
    out = {}
    for mode in ("half", "full"):
        r = subprocess.run(
            [sys.executable, "-c", _RSS_CHILD, repo, store.directory, mode,
             str(block_b), "128"],
            capture_output=True, text=True, timeout=900,
        )
        assert r.returncode == 0, r.stderr
        out[mode] = json.loads(r.stdout.strip().splitlines()[-1])
    slack = 160 << 20
    budget = 4 * out["half"]["structure_bytes"] + slack
    ok = out["half"]["rss_delta_bytes"] <= budget
    return {
        "ok": bool(ok),
        "budget_model": "4 * half-structure bytes (local CSR + tiles + "
                        "ring buckets + phi) + 160 MiB slack",
        "budget_bytes": int(budget),
        "half_rss_delta_bytes": out["half"]["rss_delta_bytes"],
        "half_structure_bytes": out["half"]["structure_bytes"],
        "half_files_read": out["half"]["files_read"],
        "host_global_rss_delta_bytes": out["full"]["rss_delta_bytes"],
        "host_global_structure_bytes": out["full"]["structure_bytes"],
        "edges_directed": store.num_directed_edges,
        "nodes": store.num_nodes,
        "block_b": block_b,
    }


def main():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    t0 = time.time()
    with tempfile.TemporaryDirectory() as tmp:
        report = {
            "gate": "store_native",
            "round": 13,
            "trajectory_identity": trajectory_identity(tmp),
            "files_read_isolation": files_read_isolation(tmp),
            "baked_seeds": baked_seeds(tmp),
            "rss_budget": rss_budget(tmp, repo),
        }
    report["pass"] = all(
        report[k]["ok"]
        for k in ("trajectory_identity", "files_read_isolation",
                  "baked_seeds", "rss_budget")
    )
    report["wall_s"] = round(time.time() - t0, 1)
    with open(ARTIFACT, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    print(json.dumps(report, indent=2, sort_keys=True))
    print(f"\n{'PASS' if report['pass'] else 'FAIL'} -> {ARTIFACT}")
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
