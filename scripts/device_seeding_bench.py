"""Device-side conductance seeding past the dense bound (VERDICT r4
item 8 / SURVEY C5 stretch).

The dense A@A scorer stops at 16,384 nodes; the degree-capped DEVICE
estimator (ops.seeding.triangle_counts_sampled_device: chunked two-hop
membership sweep, (C, cap, cap) working set, no (N, N) anything) has no
such bound. This script proves the single-chip story at ~1M nodes: score
every node's ego-net conductance ON DEVICE, compare the ranking against
the host estimator (same splitmix64 capped lists -> identical math), and
time both.

    python scripts/device_seeding_bench.py [n] [m_edges_millions] [cap] [out.json]

Defaults: N=1,000,000, 10M undirected edges, cap=64.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    m_m = float(sys.argv[2]) if len(sys.argv) > 2 else 10.0
    cap = int(sys.argv[3]) if len(sys.argv) > 3 else 64
    out_path = sys.argv[4] if len(sys.argv) > 4 else None

    import jax

    if os.environ.get("E2E_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from bigclam_tpu.ops import seeding
    from scripts.seeding_bench import build_synthetic

    rng = np.random.default_rng(5)
    t0 = time.time()
    g = build_synthetic(n, int(m_m * 1e6), rng)
    t_build = time.time() - t0

    t0 = time.time()
    phi_dev = seeding.conductance(
        g, backend="sampled_device", degree_cap=cap,
        rng=np.random.default_rng(0),
    )
    t_dev = time.time() - t0

    t0 = time.time()
    phi_host = seeding.conductance(
        g, backend="sampled", degree_cap=cap, rng=np.random.default_rng(0),
    )
    t_host = time.time() - t0

    # same capped lists + same math -> phi must agree to accumulation
    # rounding; the RANKING (what seeding consumes) must agree exactly on
    # the overwhelming majority of nodes
    close = np.isclose(phi_dev, phi_host, rtol=1e-4, atol=1e-6)
    rank_dev = np.argsort(phi_dev, kind="stable")[: max(n // 100, 10)]
    rank_host = np.argsort(phi_host, kind="stable")[: max(n // 100, 10)]
    overlap = len(set(rank_dev.tolist()) & set(rank_host.tolist())) / len(
        rank_dev
    )
    rec = {
        "bench": "device-seeding",
        "config": f"synthetic N={n} 2E={g.num_directed_edges} cap={cap}",
        "backend": jax.default_backend(),
        "seconds": {
            "graph_build": round(t_build, 1),
            "conductance_device": round(t_dev, 1),
            "conductance_host": round(t_host, 1),
        },
        "device_edges_per_sec": round(g.num_directed_edges / t_dev, 1),
        "phi_close_frac": float(close.mean()),
        "top1pct_rank_overlap": round(overlap, 4),
        "pass": bool(close.mean() > 0.999 and overlap > 0.98),
    }
    line = json.dumps(rec)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    return 0 if rec["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
