"""Comms-observability gate (ISSUE 10): prove, on CPU fakes, that the
collective-traffic accounting and the host-skew detectors do what they
claim — deterministically — and cost nothing on the trajectory.

Six check groups, the ISSUE 10 acceptance criteria verbatim:

  model_vs_measured  the static bytes-per-step model baked at step build
                     agrees (<=2% band) with the LIVE device buffers /
                     runtime counters, across dp, for all four sharded
                     trainer families: all-gather sharded, ring,
                     sparse-sharded in sparse-allreduce mode, and
                     sparse-sharded in static dense-psum mode. Scope:
                     remeasure substitutes PAYLOADS (buffer nbytes,
                     runtime counters) — it is the payload half this
                     reconciles; the occurrence COUNTS and the wire
                     conventions are pinned separately by hand-derived
                     tier-1 tests (tests/test_comms.py:
                     test_wire_byte_conventions,
                     test_ring_rotation_pays_dp_hops_per_pass,
                     test_sharded_model_arithmetic_by_hand)
  straggler          a planted per-host delay (the resilience `delay`
                     fault at site fit.step) fires EXACTLY the straggler
                     anomaly naming that host, through the single-process
                     fake-host path (two real runs merged into one
                     two-pid telemetry dir); a clean pair fires none
  imbalance          a planted unbalanced layout (locality-ordered ids,
                     balance=False — what an unbalanced cache feeds the
                     store ring) fires EXACTLY the imbalance anomaly;
                     the balanced build fires none
  identity           accounting-on trajectories are bit-identical to
                     accounting-off (the model is host-side arithmetic
                     at build time — it must never touch the math)
  overhead           the per-iteration observability path (the 3-span
                     set + heartbeat beat + the sync-duration latch the
                     comms layer added) costs < 2% of a real step at the
                     default cadence
  schema / perf diff every events.jsonl validates against obs.schema,
                     and `cli perf diff` exits 2 on an injected
                     bytes-per-step regression while passing the
                     identical re-run

    python scripts/comms_gate.py [COMMS_r14.json]

Exit 0 iff every check passes.
"""

import json
import math
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else None

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    from bigclam_tpu.utils.dist import request_cpu_devices

    request_cpu_devices(8)

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.obs import (
        RunTelemetry,
        install,
        uninstall,
        validate_events_file,
    )
    from bigclam_tpu.obs import ledger as L
    from bigclam_tpu.obs.report import load_events, render_json
    from bigclam_tpu.obs.telemetry import EVENTS_NAME
    from bigclam_tpu.parallel import (
        RingBigClamModel,
        ShardedBigClamModel,
        SparseShardedBigClamModel,
        make_mesh,
    )
    from bigclam_tpu.resilience import FaultPlan, install_plan

    checks = {}
    detail = {}

    g, _ = sample_planted_graph(
        240, 4, p_in=0.3, rng=np.random.default_rng(0)
    )
    F0 = np.random.default_rng(1).uniform(0.1, 1.0, size=(g.num_nodes, 4))

    def base_cfg(**kw):
        d = dict(num_communities=4, dtype="float64", max_iters=8,
                 conv_tol=0.0, health_every=1)
        d.update(kw)
        return BigClamConfig(**d)

    # --- 1. modeled vs measured, four families x dp -------------------
    import warnings

    agreements = {}

    def agree(name, modeled, measured):
        rel = abs(measured - modeled) / max(modeled, 1e-9)
        agreements[name] = {
            "modeled_bytes_per_step": round(modeled, 1),
            "measured_bytes_per_step": round(measured, 1),
            "rel_diff": round(rel, 6),
        }
        checks[f"agree_{name}"] = rel <= 0.02

    for dp in (2, 4):
        mesh = make_mesh((dp, 1), jax.devices()[:dp])
        m = ShardedBigClamModel(g, base_cfg(), mesh)
        st = m.init_state(F0)
        agree(f"sharded_dp{dp}", m.comms.bytes_per_step(),
              m.comms_measured(st).bytes_per_step())
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            r = RingBigClamModel(g, base_cfg(), mesh, balance=False)
        st = r.init_state(F0)
        agree(f"ring_dp{dp}", r.comms.bytes_per_step(),
              r.comms_measured(st).bytes_per_step())

    # sparse family, both static collective modes, dp=2
    K = 64
    F0w = np.zeros((g.num_nodes, K))
    F0w[:, :4] = F0
    mesh2 = make_mesh((2, 1), jax.devices()[:2])
    cfg_sp = base_cfg(
        num_communities=K, representation="sparse", sparse_m=8,
        sparse_comm_cap=16, max_iters=4,
    )
    ms = SparseShardedBigClamModel(g, cfg_sp, mesh2)
    checks["sparse_mode_is_sparse"] = ms.comm_mode == "sparse"
    stt = ms._step(ms.init_state(F0w))
    rec = ms.comms_measured(stt)
    detail["sparse_runtime"] = {
        k: rec[k] for k in ("exchanged_ids", "cap", "occupancy",
                            "dense_fallback", "exchange_bytes_per_step")
    }
    checks["sparse_exchange_within_cap"] = (
        rec["dense_fallback"] or rec["exchanged_ids"] <= rec["cap"]
    )
    modeled_ex = ms.comms.site_bytes()["sparse/allreduce_touched"]
    measured_ex = rec["exchange_bytes_per_step"]
    if rec["dense_fallback"]:
        # the runtime counters flipped the accounting to the dense psum
        modeled_ex = 2 * (K * 8) * (2 - 1) / 2 * 2   # psum formula, f64
    agree("sparse_spall_dp2_exchange", modeled_ex, measured_ex)
    mem_payload = rec["payloads"].get("sparse/all_gather_members", 0.0)
    agree("sparse_spall_dp2_members", ms.comms.sites[0].payload_bytes,
          mem_payload)

    cfg_dn = base_cfg(
        num_communities=K, representation="sparse", sparse_m=8,
        sparse_comm_cap=K, max_iters=4,
    )
    md = SparseShardedBigClamModel(g, cfg_dn, mesh2)
    checks["sparse_dense_mode_is_dense"] = md.comm_mode == "dense"
    std = md._step(md.init_state(F0w))
    recd = md.comms_measured(std)
    agree("sparse_psum_dp2_members",
          md.comms.sites[0].payload_bytes,
          recd["payloads"].get("sparse/all_gather_members", 0.0))
    checks["sparse_dense_mode_models_psum"] = (
        "sparse/psum_sumF" in md.comms.site_bytes()
        and "sparse/allreduce_touched" not in md.comms.site_bytes()
    )

    # --- 2. planted per-host delay -> straggler naming that host -----
    work = tempfile.mkdtemp(prefix="comms_gate_")

    from bigclam_tpu.utils.profiling import StageProfile

    def run_fit(tag, plan=None, iters=10):
        tdir = os.path.join(work, tag)
        tel = install(RunTelemetry(tdir, entry="fit", quiet=True))
        try:
            if plan is not None:
                install_plan(plan)
            mdl = ShardedBigClamModel(
                g, base_cfg(max_iters=iters), mesh2
            )
            # the entry-point pattern: the loop runs under a "fit" stage
            # span — the parent the overhead rule attributes against
            with StageProfile().stage("fit"):
                res = mdl.fit(F0)
            tel.set_final({"llh": res.llh, "iters": res.num_iters,
                           "n": g.num_nodes, "edges": g.num_edges,
                           "k": 4, "mesh": "2x1"})
            rep = tel.finalize()
        finally:
            install_plan(None)
            uninstall(tel)
        return tdir, rep, res

    a_dir, a_rep, a_res = run_fit("baseline")
    delay_plan = FaultPlan([
        {"kind": "delay", "site": "fit.step", "at": it, "seconds": 0.3}
        for it in (1, 2, 3, 4)
    ])
    b_dir, b_rep, _ = run_fit("delayed", plan=delay_plan)

    def merge_two(tag, rep0, rep1):
        mdir = os.path.join(work, tag)
        os.makedirs(mdir, exist_ok=True)
        shutil.copy(
            os.path.join(a_dir, EVENTS_NAME),
            os.path.join(mdir, EVENTS_NAME),
        )
        with open(os.path.join(mdir, "run_report.json"), "w") as f:
            json.dump(rep0, f)
        r1 = dict(rep1, pid=1, processes=2)
        r1["fingerprint"] = dict(
            rep1.get("fingerprint", {}), host="fake-host-1"
        )
        with open(os.path.join(mdir, "run_report.p1.json"), "w") as f:
            json.dump(r1, f)
        obj, errors = render_json(mdir)
        return [
            x for x in obj["anomalies"] if x.get("source") == "report"
        ], errors

    rep0 = dict(a_rep, processes=2)
    found, errs = merge_two("merged_delay", rep0, b_rep)
    detail["straggler_findings"] = found
    checks["straggler_fires_exactly_once"] = len(found) == 1
    checks["straggler_names_delayed_host"] = bool(found) and (
        found[0]["check"] == "straggler"
        and found[0]["pid"] == 1
        and found[0]["host"] == "fake-host-1"
    )
    a2_dir, a2_rep, _ = run_fit("baseline2")
    clean, _ = merge_two("merged_clean", rep0, a2_rep)
    checks["clean_pair_fires_nothing"] = clean == []

    # --- 3. planted unbalanced layout -> imbalance anomaly -----------
    g_loc, _ = sample_planted_graph(
        256, 8, p_in=0.9, rng=np.random.default_rng(2)
    )
    mesh4 = make_mesh((4, 1), jax.devices()[:4])

    def build_ring(tag, balance):
        tdir = os.path.join(work, tag)
        tel = install(RunTelemetry(tdir, entry="fit", quiet=True))
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                RingBigClamModel(
                    g_loc, base_cfg(num_communities=8), mesh4,
                    balance=balance,
                )
            tel.finalize()
        finally:
            uninstall(tel)
        return [
            e for e in (load_events(tdir) or [])
            if e.get("kind") == "anomaly"
        ], tdir

    anoms, imb_dir = build_ring("imbalanced", balance=False)
    detail["imbalance_anomalies"] = anoms
    checks["imbalance_fires"] = bool(anoms)
    checks["imbalance_fires_exactly"] = bool(anoms) and all(
        e["check"] == "imbalance" for e in anoms
    )
    clean_anoms, _ = build_ring("balanced", balance=True)
    checks["balanced_fires_nothing"] = clean_anoms == []

    # --- 4. accounting-on bit-identity -------------------------------
    off_res = ShardedBigClamModel(
        g, base_cfg(max_iters=10), mesh2
    ).fit(F0)
    checks["accounting_on_bit_identical"] = bool(
        np.array_equal(a_res.F, off_res.F)
        and a_res.llh_history == off_res.llh_history
    )

    # --- 5. per-iteration observability overhead < 2% ----------------
    from bigclam_tpu.obs import trace as obs_trace
    from bigclam_tpu.utils.profiling import step_time

    g_big, _ = sample_planted_graph(
        4000, 16, p_in=0.2, rng=np.random.default_rng(3)
    )
    from bigclam_tpu.models import BigClamModel

    big = BigClamModel(g_big, base_cfg(num_communities=16, max_iters=2,
                                       health_every=10))
    Fb = np.random.default_rng(4).uniform(
        0.1, 1.0, size=(g_big.num_nodes, 16)
    )
    sec_per_step = step_time(big._step, big.init_state(Fb), steps=10,
                             warmup=2)
    tel = install(RunTelemetry(os.path.join(work, "ovh"), entry="fit",
                               quiet=True))
    try:
        iters = 3000
        t0 = time.perf_counter()
        for i in range(iters):
            # the full per-iteration on-path: the 3-span set (incl. the
            # sync-duration latch this PR added) + the heartbeat beat
            with obs_trace.span("fit_loop/dispatch", emit=False):
                pass
            with obs_trace.span("fit_loop/sync", emit=False):
                pass
            with obs_trace.span("fit_loop/callback", emit=False):
                pass
            tel.step_beat(i, -1.0)
        per_iter = (time.perf_counter() - t0) / iters
        tel.finalize()
    finally:
        uninstall(tel)
    detail["overhead"] = {
        "sec_per_step": round(sec_per_step, 6),
        "obs_path_per_iter": round(per_iter, 9),
        "fraction": round(per_iter / sec_per_step, 6),
    }
    checks["overhead_under_2pct"] = per_iter < 0.02 * sec_per_step

    # --- 6. schema validity + perf diff on injected bytes regression -
    schema_errors = []
    for tdir in (a_dir, b_dir, imb_dir):
        _, errors = validate_events_file(os.path.join(tdir, EVENTS_NAME))
        schema_errors.extend(errors[:3])
    checks["all_events_schema_valid"] = not schema_errors

    ledger_path = os.path.join(work, "ledger.jsonl")
    led = L.PerfLedger(ledger_path)
    a_events = load_events(a_dir) or []
    secs = [e["sec_per_iter"] for e in a_events
            if e.get("kind") == "step"
            and isinstance(e.get("sec_per_iter"), (int, float))]
    base_rec = L.build_record(a_rep, secs or [0.01] * 10)
    checks["record_carries_comms"] = isinstance(
        base_rec.get("comms_bytes_per_step"), float
    ) and base_rec["comms_bytes_per_step"] > 0
    checks["record_carries_shape"] = (
        base_rec.get("processes") == 1
        and base_rec.get("mesh") == "2x1"
    )
    led.append(base_rec)
    same = dict(base_rec, run="rerun", ts=base_rec["ts"] + 1)
    led.append(same)
    from bigclam_tpu.cli import main as cli_main

    rc_same = cli_main(["perf", "diff", "--ledger", ledger_path])
    checks["perf_diff_passes_identical"] = rc_same == 0
    injected = dict(
        base_rec, run="injected-bytes", ts=base_rec["ts"] + 2,
        comms_bytes_per_step=round(
            base_rec["comms_bytes_per_step"] * 2.0, 1
        ),
        comms_sites={
            k: round(v * 2.0, 1)
            for k, v in base_rec["comms_sites"].items()
        },
    )
    led.append(injected)
    rc_inj = cli_main(["perf", "diff", "--ledger", ledger_path])
    checks["perf_diff_flags_injected_bytes"] = rc_inj == 2
    detail["perf_diff"] = {"identical_rc": rc_same, "injected_rc": rc_inj}

    ok = all(checks.values())
    artifact = {
        "gate": "comms_r14",
        "created_unix": round(time.time(), 1),
        "pass": ok,
        "checks": checks,
        "agreements": agreements,
        "detail": detail,
        "device": str(jax.devices()[0]),
        "jax": jax.__version__,
        "note": (
            "static bytes/step model vs live buffers within 2% across "
            "dp for sharded/ring/sparse(spall)/sparse(psum); planted "
            "per-host delay -> exactly one straggler anomaly naming the "
            "delayed fake host; locality-ordered unbalanced ring -> "
            "exactly the imbalance anomaly; accounting-on bit-identical; "
            "per-iteration observability path < 2% of a 123K-edge step; "
            "events schema-valid; cli perf diff exit 2 on 2x injected "
            "bytes/step, exit 0 on the identical re-run."
        ),
    }
    line = json.dumps(artifact, sort_keys=True)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    if not ok:
        bad = sorted(k for k, v in checks.items() if not v)
        print(f"FAILED checks: {bad}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
