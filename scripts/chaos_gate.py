"""Chaos gate (ISSUE 5 satellite): run the deterministic kill/corrupt/NaN
matrix against the REAL CLI entry points and emit one JSON artifact line.

Scenarios (all seeded, all on CPU, all through `python -m bigclam_tpu.cli`):

  kill_resume     SIGKILL the fit mid-iteration (BIGCLAM_FAULTS kill fault),
                  then rerun with the default `--resume auto`: the final F
                  must be BIT-identical to an uninterrupted run, with the
                  resume recorded in the telemetry lineage.
  nan_rollback    inject a NaN into F at a chosen iteration: the fit must
                  recover via non-finite rollback (a `rollback` event, no
                  FloatingPointError) and complete with a finite LLH.
  shard_quarantine corrupt a cache shard blob on disk: the fit must
                  quarantine + rebuild it from the source edge list
                  (`quarantine` event), complete, and leave the cache
                  crc-valid.

Every scenario's events.jsonl must validate against the telemetry schema.

    python scripts/chaos_gate.py [out.json]

Exit 0 iff every check passes. The committed artifact (CHAOS_r09.json) is
the proof the recovery paths ran at the commit that shipped them; the same
matrix runs in tier-1 (tests/test_resilience.py, `chaos` marker).
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _write_graph(path: str) -> None:
    edges = []
    for base in (0, 10):
        for i in range(10):
            for j in range(i + 1, 10):
                edges.append((base + i, base + j))
    edges.append((9, 10))
    with open(path, "w") as f:
        f.write("\n".join(f"{u} {v}" for u, v in edges))


def _cli(*argv, faults=None, check=True):
    env = {k: v for k, v in os.environ.items() if k != "BIGCLAM_FAULTS"}
    if faults is not None:
        env["BIGCLAM_FAULTS"] = json.dumps(faults)
    r = subprocess.run(
        [sys.executable, "-m", "bigclam_tpu.cli", *argv],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    if check and r.returncode != 0:
        raise RuntimeError(f"cli {argv[0]} failed:\n{r.stdout}\n{r.stderr}")
    return r


def _schema_ok(tdir: str):
    from bigclam_tpu.obs.schema import validate_events_file

    n, errors = validate_events_file(os.path.join(tdir, "events.jsonl"))
    return n, errors


def _kinds(tdir: str):
    out = {}
    with open(os.path.join(tdir, "events.jsonl")) as f:
        for line in f:
            if line.strip():
                k = json.loads(line).get("kind")
                out[k] = out.get(k, 0) + 1
    return out


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else None
    work = tempfile.mkdtemp(prefix="chaos_gate_")
    graph = os.path.join(work, "g.txt")
    _write_graph(graph)
    base = [
        "fit", "--graph", graph, "--k", "2", "--dtype", "float64",
        "--max-iters", "12", "--conv-tol", "0", "--init", "random",
        "--quiet", "--platform", "cpu",
    ]
    scenarios = {}
    checks = {}

    # --- reference: uninterrupted run ---
    ref_f = os.path.join(work, "ref.npy")
    _cli(*base, "--checkpoint-dir", os.path.join(work, "ck_ref"),
         "--checkpoint-every", "3", "--save-f", ref_f)
    ref = np.load(ref_f)

    # --- (a) kill -9 mid-fit, then --resume auto ---
    ck = os.path.join(work, "ck_kill")
    tdir = os.path.join(work, "telem_kill")
    r = _cli(
        *base, "--checkpoint-dir", ck, "--checkpoint-every", "3",
        "--telemetry-dir", tdir,
        faults={"faults": [{"kind": "kill", "site": "fit.step", "at": 8}]},
        check=False,
    )
    resumed_f = os.path.join(work, "resumed.npy")
    _cli(*base, "--checkpoint-dir", ck, "--checkpoint-every", "3",
         "--telemetry-dir", tdir, "--save-f", resumed_f)
    from bigclam_tpu.resilience import read_lineage

    lineage = read_lineage(tdir)
    n_ev, errors = _schema_ok(tdir)
    kinds = _kinds(tdir)
    bit_identical = bool(np.array_equal(np.load(resumed_f), ref))
    scenarios["kill_resume"] = {
        "killed_rc": r.returncode,
        "resumed_from_step": lineage[0]["resumed_step"] if lineage else None,
        "bit_identical_F": bit_identical,
        "events": {k: kinds[k] for k in ("fault_injected", "resume",
                                         "checkpoint", "restore")
                   if k in kinds},
        "schema_errors": errors[:5],
    }
    checks["kill_was_sigkill"] = r.returncode != 0
    checks["kill_resume_bit_identical"] = bit_identical
    checks["kill_resume_lineage_recorded"] = bool(lineage)
    checks["kill_resume_schema_valid"] = not errors

    # --- (b) NaN injection -> rollback recovery ---
    tdir = os.path.join(work, "telem_nan")
    r = _cli(
        *base, "--telemetry-dir", tdir,
        faults={"faults": [{"kind": "nan_inject", "site": "fit.step",
                            "at": 5}]},
    )
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    n_ev, errors = _schema_ok(tdir)
    kinds = _kinds(tdir)
    scenarios["nan_rollback"] = {
        "final_llh": rec["llh"],
        "iters": rec["iters"],
        "rollbacks": kinds.get("rollback", 0),
        "schema_errors": errors[:5],
    }
    checks["nan_recovered_finite"] = bool(np.isfinite(rec["llh"]))
    checks["nan_rollback_event"] = kinds.get("rollback", 0) >= 1
    checks["nan_completed_no_abort"] = rec["iters"] == 12
    checks["nan_schema_valid"] = not errors

    # --- (c) corrupted shard -> quarantine + re-ingest ---
    cache = os.path.join(work, "g.cache")
    _cli("ingest", "--graph", graph, "--cache-dir", cache, "--shards", "4",
         "--chunk-bytes", "2048")
    from bigclam_tpu.graph.store import GraphStore

    store = GraphStore.open(cache)
    blob = store.shard_files(1)[1]
    size = os.path.getsize(blob)
    with open(blob, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))
    tdir = os.path.join(work, "telem_shard")
    heal_f = os.path.join(work, "healed.npy")
    r = _cli(*base[:2], cache, *base[3:], "--telemetry-dir", tdir,
             "--save-f", heal_f)
    n_ev, errors = _schema_ok(tdir)
    kinds = _kinds(tdir)
    # the healed cache must be crc-valid under a strict reopen
    crc_valid = True
    try:
        GraphStore.open(cache).load_graph()
    except Exception:
        crc_valid = False
    scenarios["shard_quarantine"] = {
        "quarantine_events": kinds.get("quarantine", 0),
        "quarantined_files": sorted(
            os.listdir(os.path.join(cache, "quarantine"))
        ),
        "rebuilt_cache_crc_valid": crc_valid,
        "fit_F_matches_reference": bool(
            np.array_equal(np.load(heal_f), ref)
        ),
        "schema_errors": errors[:5],
    }
    checks["shard_quarantined"] = kinds.get("quarantine", 0) == 1
    checks["shard_rebuilt_crc_valid"] = crc_valid
    checks["shard_fit_matches_reference"] = scenarios["shard_quarantine"][
        "fit_F_matches_reference"
    ]
    checks["shard_schema_valid"] = not errors

    import jax

    record = {
        "gate": "chaos",
        "config": "two 10-cliques + bridge, K=2 f64 cpu, max_iters=12, "
                  "seed 0; kill@8 / nan@5 / shard-1 byte flip",
        "scenarios": scenarios,
        "checks": checks,
        "jax": jax.__version__,
        "pass": all(checks.values()),
    }
    line = json.dumps(record)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    shutil.rmtree(work, ignore_errors=True)
    return 0 if record["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
