"""Incremental-delta gate (ISSUE 15): prove on CPU, fast enough for CI,
that the continuous delta pipeline delivers its contract:

  delta_reingest    appending a 1% edge delta to a compiled cache
                    rebuilds ONLY the touched node ranges: untouched
                    shard blobs byte-identical, files_read = exactly the
                    touched shards' blobs (+ raw_ids), the merged graph
                    bit-identical to a from-scratch build of the
                    combined text, and the apply is >= 5x faster than a
                    full re-ingest
  warm_refit        `cli refit` from the previous published F lands a
                    global LLH within the gate band of a FROM-SCRATCH
                    fit on the post-delta graph at <= 25% of its
                    wall-clock and sweep count, with refit_cost_ratio +
                    touched_frac recorded in the perf ledger, an
                    identical re-run diffing PASS, and a fit record
                    never baselining a refit record
  continuous_loop   the fit -> publish -> serve loop: follow_deltas
                    streams 2 delta files through re-ingest + refit +
                    publish while a live `serve` query stream runs —
                    >= 2 generations hot-swap, ZERO dropped queries,
                    and served answers reflect the newest generation

Emits one JSON artifact (DELTA_r19.json); exit 0 iff every check passes.

    python scripts/delta_gate.py [out.json]
"""

import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# --- ingest-speed workload (timing needs a parse-bound full re-ingest)
ING_N = 20_000
ING_EXTRA = 80_000
ING_SHARDS = 16
SPEEDUP_FLOOR = 5.0

# --- refit workload (planted; big enough that the from-scratch fit
# costs real work and the 1% delta touches a small fraction even with
# a 1-hop halo)
N = 2400
K = 24
P_IN = 0.3
CONV_TOL = 1e-5
LLH_BAND = 0.05           # |1 - LLH_refit / LLH_scratch| ceiling
COST_CEIL = 0.25          # steady-state refit wall / scratch-fit wall


def _write_edges(path, edges):
    with open(path, "w") as f:
        for u, v in edges:
            f.write(f"{u}\t{v}\n")


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else None

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from bigclam_tpu.cli import main as cli_main
    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.graph import build_graph
    from bigclam_tpu.graph.store import GraphStore, compile_graph_cache
    from bigclam_tpu.models import BigClamModel, follow_deltas
    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.obs import ledger as L
    from bigclam_tpu.ops.objective import loglikelihood
    from bigclam_tpu.serve.server import MembershipServer
    from bigclam_tpu.serve.snapshot import (
        ServingSnapshot,
        publish_snapshot,
    )
    from bigclam_tpu.utils.checkpoint import CheckpointManager

    workdir = tempfile.mkdtemp(prefix="delta_gate_")
    checks = {}
    record = {"gate": "delta", "n": N, "k": K, "p_in": P_IN}

    # ============================================================
    # 1) delta re-ingest: touched ranges only, >= 5x over full
    # ============================================================
    rng = np.random.default_rng(0)
    base = [(i, (i + 1) % ING_N) for i in range(ING_N)]
    base += [
        (int(u), int(v))
        for u, v in rng.integers(0, ING_N, (ING_EXTRA, 2))
        if u != v
    ]
    text = os.path.join(workdir, "big.txt")
    _write_edges(text, base)
    cache = os.path.join(workdir, "big.cache")
    t0 = time.perf_counter()
    store = compile_graph_cache(
        text, cache, num_shards=ING_SHARDS, seed_bake=False
    )
    full_ingest_s = time.perf_counter() - t0
    rows = store.rows_per_shard
    # ~1% delta confined to shard 0's row range (ring makes internal
    # row == raw id, so the target shard is known by construction)
    n_delta = (ING_N + ING_EXTRA) // 100
    dpairs = set()
    drng = np.random.default_rng(1)
    while len(dpairs) < n_delta:
        u, v = (int(x) for x in drng.integers(0, rows, 2))
        if u != v:
            dpairs.add((u, v))
    delta = os.path.join(workdir, "delta.txt")
    _write_edges(delta, sorted(dpairs))
    before = {}
    for s in range(ING_SHARDS):
        ip, dx = store.shard_files(s)
        before[s] = (open(ip, "rb").read(), open(dx, "rb").read())
    t0 = time.perf_counter()
    info = store.apply_delta(delta)
    delta_s = time.perf_counter() - t0
    # full re-ingest of the combined text — what the delta path replaces
    combined = os.path.join(workdir, "combined.txt")
    with open(combined, "w") as f:
        f.write(open(text).read())
        f.write(open(delta).read())
    t0 = time.perf_counter()
    compile_graph_cache(
        combined, os.path.join(workdir, "full.cache"),
        num_shards=ING_SHARDS, seed_bake=False,
    )
    reingest_s = time.perf_counter() - t0
    speedup = reingest_s / max(delta_s, 1e-9)
    touched = set(info["touched_shards"])
    untouched_ok = True
    for s in range(ING_SHARDS):
        ip, dx = store.shard_files(s)
        same = (
            open(ip, "rb").read(), open(dx, "rb").read()
        ) == before[s]
        if s in touched:
            continue
        untouched_ok &= same
    expect_files = {"raw_ids.npy"}
    for s in touched:
        expect_files |= {
            f"shard_{s:05d}.indptr.npy", f"shard_{s:05d}.indices.npy"
        }
    g_delta = GraphStore.open(cache).load_graph()
    g_full = build_graph(combined)
    merged_ok = (
        np.array_equal(np.asarray(g_delta.indptr),
                       np.asarray(g_full.indptr))
        and np.array_equal(np.asarray(g_delta.indices),
                           np.asarray(g_full.indices))
        and np.array_equal(g_delta.raw_ids, g_full.raw_ids)
    )
    record["reingest"] = {
        "edges": len(base),
        "delta_edges": n_delta,
        "shards": ING_SHARDS,
        "touched_shards": sorted(touched),
        "full_ingest_s": round(full_ingest_s, 3),
        "full_reingest_s": round(reingest_s, 3),
        "delta_apply_s": round(delta_s, 4),
        "speedup": round(speedup, 1),
        "files_read": list(info["files_read"]),
        "touched_frac": info["touched_frac"],
    }
    checks["reingest_touched_shards_only"] = touched == {0}
    checks["reingest_untouched_blobs_byte_identical"] = bool(
        untouched_ok
    )
    checks["reingest_files_read_contract"] = (
        set(info["files_read"]) == expect_files
    )
    checks["reingest_merged_bit_identical_to_full_build"] = bool(
        merged_ok
    )
    checks["reingest_speedup_5x"] = speedup >= SPEEDUP_FLOOR

    # ============================================================
    # 2) warm-start refit: LLH band at <= 25% of a scratch fit
    # ============================================================
    prng = np.random.default_rng(7)
    g0, truth = sample_planted_graph(N, K, p_in=P_IN, rng=prng)
    ptext = os.path.join(workdir, "planted.txt")
    _write_edges(
        ptext,
        [
            (int(g0.raw_ids[u]), int(g0.raw_ids[v]))
            for u, v in zip(g0.src, g0.dst)
            if u < v
        ],
    )
    pcache = os.path.join(workdir, "planted.cache")
    pstore = compile_graph_cache(ptext, pcache, num_shards=8)
    cfg = BigClamConfig(
        num_communities=K, max_iters=500, conv_tol=CONV_TOL
    )
    g1 = pstore.load_graph()
    model1 = BigClamModel(g1, cfg)
    res1 = model1.fit(model1.random_init())
    # 1% delta: fresh in-community pairs inside the first two planted
    # blocks (touched rows stay a small fraction of N even with halo)
    size = N // K
    existing = {
        (int(u), int(v)) for u, v in zip(g1.src, g1.dst)
    }
    dd = set()
    drng = np.random.default_rng(5)
    want = max(g1.num_edges // 100, 12)
    while len(dd) < want:
        c = int(drng.integers(0, 2))
        u, v = (
            int(x) for x in drng.integers(c * size, (c + 1) * size, 2)
        )
        if u != v and (u, v) not in existing:
            dd.add((min(u, v), max(u, v)))
    pdelta = os.path.join(workdir, "planted_delta.txt")
    _write_edges(pdelta, sorted(dd))
    pstore.apply_delta(pdelta)
    g2 = pstore.load_graph()
    # from-scratch fit on the post-delta graph (the cost baseline);
    # model build + compile excluded the same way the refit run's
    # engine compile is excluded below (warm both, time the work)
    model2 = BigClamModel(g2, cfg)
    F0_scratch = model2.random_init()
    t0 = time.perf_counter()
    scratch = model2.fit(F0_scratch)
    scratch_s = time.perf_counter() - t0
    llh_scratch = scratch.llh
    snaps = os.path.join(workdir, "snaps")
    publish_snapshot(
        snaps, step=res1.num_iters, F=res1.F, raw_ids=g1.raw_ids,
        num_edges=g2.num_edges, cfg=cfg,
        meta={"llh": res1.llh, "fit_wall_s": round(scratch_s, 4),
              "fit_iters": scratch.num_iters},
    )
    ledger_path = os.path.join(workdir, "ledger.jsonl")

    def run_refit(tag):
        import contextlib
        import io

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli_main([
                "refit", "--graph", pcache, "--snapshots", snaps,
                "--delta", pdelta, "--quiet",
                "--telemetry-dir", os.path.join(workdir, f"tel_{tag}"),
                "--perf-ledger", ledger_path,
            ])
        out = json.loads(buf.getvalue().strip().splitlines()[-1])
        return rc, out

    rc1, ref1 = run_refit("r1")       # cold: pays the fold-in compile
    rc2, ref2 = run_refit("r2")       # steady-state (the loop's figure:
    #                                   one compile serves every delta)
    snap_final = ServingSnapshot.load(snaps)
    mf = BigClamModel(g2, cfg)
    stf = mf.init_state(
        np.asarray(snap_final.F[:N, :K], np.float64)
    )
    llh_refit = float(
        loglikelihood(stf.F, stf.sumF, mf.edges, cfg)
    )
    rel = abs(1.0 - llh_refit / llh_scratch)
    record["refit"] = {
        "scratch_fit_s": round(scratch_s, 3),
        "scratch_iters": scratch.num_iters,
        "scratch_llh": llh_scratch,
        "refit_wall_s": ref1["refit_wall_s"],
        "refit_rounds": ref1["rounds"],
        "touched_frac": ref1["touched_frac"],
        "refit_llh": llh_refit,
        "llh_rel_gap": round(rel, 6),
        "escalated": ref1["escalated"],
        "cold_cost_ratio": ref1["refit_cost_ratio"],
        "steady_cost_ratio": ref2["refit_cost_ratio"],
    }
    checks["refit_cli_ok"] = rc1 == 0 and rc2 == 0
    checks["refit_llh_in_band"] = rel <= LLH_BAND
    # the continuous loop's per-delta cost: the fold-in compile is paid
    # once per process (models.refit._cached_foldin_fit), so the
    # steady-state run is the honest "fraction of a from-scratch fit"
    checks["refit_wall_under_25pct"] = (
        ref2["refit_cost_ratio"] is not None
        and ref2["refit_cost_ratio"] <= COST_CEIL
    )
    checks["refit_sweeps_under_25pct"] = (
        ref1["rounds"] <= scratch.num_iters * COST_CEIL
    )
    checks["refit_not_escalated"] = not ref1["escalated"]
    # ledger: both runs recorded with the verdicted fields; identical
    # re-run diffs PASS; a fit record can never baseline a refit
    led = L.PerfLedger(ledger_path)
    recs = led.load()
    refit_recs = [r for r in recs if r.get("entry") == "refit"]
    checks["refit_ledger_fields_recorded"] = (
        len(refit_recs) >= 2
        and all(
            r.get("refit_cost_ratio") is not None
            and r.get("touched_frac") is not None
            for r in refit_recs
        )
    )
    base_rec = led.baseline_for(refit_recs[-1], recs)
    diff_pass = False
    if base_rec is not None:
        d = L.diff_records(base_rec, refit_recs[-1])
        diff_pass = not d["regression"] and any(
            c["metric"] == "refit_cost_ratio" for c in d["checks"]
        )
    checks["refit_identical_rerun_diff_pass"] = diff_pass
    fit_like = dict(refit_recs[-1], entry="fit")
    checks["refit_never_baselines_fit"] = (
        led.baseline_for(refit_recs[0], recs) is None
        and L.match_key(fit_like) != L.match_key(refit_recs[-1])
    )

    # ============================================================
    # 3) the continuous loop under a live query stream
    # ============================================================
    loop_snaps = os.path.join(workdir, "loop_snaps")
    publish_snapshot(
        loop_snaps, step=1, F=scratch.F, raw_ids=g2.raw_ids,
        num_edges=g2.num_edges, cfg=cfg,
        meta={"fit_wall_s": round(scratch_s, 4)},
    )
    ddir = os.path.join(workdir, "loop_deltas")
    os.makedirs(ddir)
    server = MembershipServer(
        loop_snaps, store=GraphStore.open(pcache),
        budget_s=0.002, max_batch=32, watch_interval_s=0.05,
    )
    stream_stop = threading.Event()
    stream = {"answers": 0, "errors": 0, "generations": set()}

    def query_stream():
        qrng = np.random.default_rng(13)
        while not stream_stop.is_set():
            u = int(g2.raw_ids[int(qrng.integers(0, N))])
            try:
                r = server.query(
                    {"family": "communities_of", "u": u}, timeout=30.0
                )
            except Exception:   # noqa: BLE001
                stream["errors"] += 1
                continue
            stream["answers"] += 1
            if "error" in r:
                stream["errors"] += 1
            stream["generations"].add(server.generation)
            time.sleep(0.002)

    streamer = threading.Thread(target=query_stream, daemon=True)
    streamer.start()
    # two more deltas (fresh in-community pairs in later blocks), fed
    # ONE AT A TIME with a wait for the server to swap in between —
    # every published generation must be OBSERVED serving, not skipped
    loop_out = {"generations": 0, "escalations": 0, "last_step": None}
    F_loop = scratch.F
    for j, block in enumerate((2, 3)):
        pairs = set()
        jrng = np.random.default_rng(20 + j)
        while len(pairs) < 15:
            u, v = (
                int(x)
                for x in jrng.integers(block * size, (block + 1) * size, 2)
            )
            if u != v and (u, v) not in existing:
                pairs.add((min(u, v), max(u, v)))
        _write_edges(
            os.path.join(ddir, f"delta_{j:03d}.txt"), sorted(pairs)
        )
        step_out = follow_deltas(
            pstore, cfg, F_loop, loop_snaps, ddir,
            max_deltas=1, timeout_s=60, interval_s=0.05, quiet=True,
        )
        loop_out["generations"] += step_out["generations"]
        loop_out["escalations"] += step_out["escalations"]
        loop_out["last_step"] = step_out["last_step"]
        F_loop = None        # next round restarts from the cache state
        deadline = time.time() + 15
        while server.generation != step_out["last_step"] and (
            time.time() < deadline
        ):
            time.sleep(0.05)
        time.sleep(0.2)      # let the stream observe this generation
        if F_loop is None:
            snap_now = ServingSnapshot.load(loop_snaps)
            F_loop = np.asarray(snap_now.F[:N, :K], np.float64)
    stream_stop.set()
    streamer.join(timeout=10)
    stats = server.stats()
    final_snap = ServingSnapshot.load(loop_snaps)
    # served answers reflect the newest generation: a touched node's
    # communities_of answer equals the final snapshot's threshold read
    flipped_ok = True
    for u in range(2 * size, 4 * size, 7):
        r = server.query(
            {"family": "communities_of", "u": int(g2.raw_ids[u])}
        )
        row = final_snap.row_of(int(g2.raw_ids[u]))
        cids, _ = final_snap.communities_of(row)
        flipped_ok &= (
            sorted(c for c, _ in r["communities"])
            == sorted(int(c) for c in cids)
        )
    server.close()
    record["loop"] = {
        "generations_published": loop_out["generations"],
        "last_step": loop_out["last_step"],
        "escalations": loop_out["escalations"],
        "swaps": stats["snapshot_swaps"],
        "stream_answers": stream["answers"],
        "stream_errors": stream["errors"],
        "generations_seen": sorted(stream["generations"]),
        "serve_errors": stats["serve_errors"],
    }
    checks["loop_two_generations_published"] = (
        loop_out["generations"] >= 2
    )
    checks["loop_server_swapped_each_generation"] = (
        stats["snapshot_swaps"] >= 2
        and server_final_ok(stats, loop_out)
    )
    checks["loop_zero_dropped_queries"] = (
        stream["answers"] > 0
        and stream["errors"] == 0
        and stats["serve_errors"] == 0
    )
    checks["loop_answers_track_newest_generation"] = bool(flipped_ok)

    record["checks"] = checks
    record["pass"] = all(checks.values())
    text_out = json.dumps(record, indent=2, sort_keys=True)
    print(text_out)
    if out_path:
        with open(out_path, "w") as f:
            f.write(text_out + "\n")
    shutil.rmtree(workdir, ignore_errors=True)
    return 0 if record["pass"] else 1


def server_final_ok(stats, loop_out) -> bool:
    return stats["snapshot_step"] == loop_out["last_step"]


if __name__ == "__main__":
    sys.exit(main())
