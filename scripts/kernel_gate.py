"""Fused-superstep kernel gate (ISSUE 13): prove, in interpret mode on
CPU, that the fused Pallas edge superstep engages on all four trainer
families with no silent XLA fallback, computes the XLA path's
trajectories, closes the grouped/K-blocked large-K store-layout gap,
that the sparse member-merge kernel is EXACT against the searchsorted
merge, and that the re-priced memory/roofline models verdict the fd
elimination.

Check groups, the ISSUE 13 acceptance criteria verbatim:

  parity          fused interpret-mode trajectories vs the XLA path
                  across single-chip / sharded (dp2, 2x2 TP, K-blocked)
                  / ring (flat + K-blocked) — LLH-band (the fusion
                  reorders accumulation); fused-vs-split first step
                  bitwise; NO XLA fallback recorded anywhere (every
                  engaged_path asserted fused)
  store_native    store-built fused fits bit-identical to in-memory
                  fused fits, INCLUDING the K-blocked large-K store
                  layout that used to fall back to XLA (the closed gap)
  sparse_merge    the Pallas member-merge kernel EXACT vs the
                  searchsorted merge (incl. sentinel rows), and full
                  sparse fits (M < K truncation regime; single-chip +
                  sharded) bit-identical under the kernel
  bytes_model     modeled bytes-per-step for the fused path <= 0.6x the
                  split-kernel model at the K=128 bench point (the fd
                  elimination), on BOTH the roofline cost model and the
                  memory model's dst-row transient
  ledger          kernel_path joins the perf-ledger match key: a fused
                  record never baselines against a split/xla record
                  (`cli perf diff` exits 1 = no baseline), while the
                  identical fused re-run passes (exit 0)

    python scripts/kernel_gate.py [KERNEL_r17.json]

Exit 0 iff every check passes. Real-chip hbm_frac >= 0.6 stays with the
ROADMAP item 1 pod drill — this gate is the CPU-side semantic half.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else None

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    from bigclam_tpu.utils.dist import request_cpu_devices

    request_cpu_devices(8)

    import jax.numpy as jnp  # noqa: F401

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.graph.ingest import graph_from_edges
    from bigclam_tpu.graph.store import compile_graph_cache
    from bigclam_tpu.models.bigclam import BigClamModel
    from bigclam_tpu.models.sparse import SparseBigClamModel
    from bigclam_tpu.parallel import (
        RingBigClamModel,
        ShardedBigClamModel,
        StoreRingBigClamModel,
        StoreShardedBigClamModel,
        make_mesh,
    )
    from bigclam_tpu.parallel.sparse_sharded import SparseShardedBigClamModel

    checks = {}
    detail = {}
    work = tempfile.mkdtemp(prefix="kernel_gate_")

    rng = np.random.default_rng(0)
    n = 64
    a = rng.random((n, n)) < 0.15
    edges = [(i, j) for i in range(n) for j in range(i + 1, n) if a[i, j]]
    edges.append((0, n - 1))
    g = graph_from_edges(edges, num_nodes=n)

    def cfg(**kw):
        base = dict(
            num_communities=6, dtype="float32", edge_chunk=64,
            use_pallas_csr=True, pallas_interpret=True,
            csr_block_b=8, csr_tile_t=8, max_iters=6, conv_tol=0.0,
        )
        base.update(kw)
        return BigClamConfig(**base)

    def steps(model, F0, k):
        s = model.init_state(F0)
        for _ in range(4):
            s = model._step(s)
        return np.asarray(s.F)[:n, :k], float(s.llh)

    # --- 1. parity: fused vs XLA, every family, paths asserted ----------
    paths = {}
    band = {}

    def parity(tag, m_fused, m_xla, k, want):
        F0 = np.random.default_rng(7).uniform(0.0, 1.0, (n, k))
        Ff, lf = steps(m_fused, F0, k)
        Fx, lx = steps(m_xla, F0, k)
        paths[tag] = m_fused.engaged_path
        rel = abs(1.0 - lf / lx)
        band[tag] = {"rel_llh": rel, "max_dF": float(np.abs(Ff - Fx).max())}
        checks[f"path_{tag}"] = m_fused.engaged_path == want
        checks[f"parity_{tag}"] = rel < 1e-5 and np.allclose(
            Ff, Fx, rtol=3e-5, atol=3e-5
        )

    c = cfg()
    ckb = cfg(num_communities=12, csr_k_block=3)
    x = cfg(use_pallas_csr=False)
    xkb = cfg(num_communities=12, use_pallas_csr=False)
    parity("single", BigClamModel(g, c), BigClamModel(g, x), 6, "csr_fused")
    parity(
        "single_kb", BigClamModel(g, ckb), BigClamModel(g, xkb), 12,
        "csr_fused_kb",
    )
    mesh2 = make_mesh((2, 1), jax.devices()[:2])
    mesh22 = make_mesh((2, 2), jax.devices()[:4])
    parity(
        "sharded_dp2",
        ShardedBigClamModel(g, c, mesh2),
        ShardedBigClamModel(g, x, mesh2), 6, "csr_fused",
    )
    parity(
        "sharded_2x2_tp",
        ShardedBigClamModel(g, c, mesh22),
        ShardedBigClamModel(g, x, mesh22), 6, "csr_fused",
    )
    parity(
        "sharded_dp2_kb",
        ShardedBigClamModel(g, ckb, mesh2),
        ShardedBigClamModel(g, xkb, mesh2), 12, "csr_fused_kb",
    )
    parity(
        "ring_dp2",
        RingBigClamModel(g, c, mesh2),
        RingBigClamModel(g, x, mesh2), 6, "csr_ring_fused",
    )
    parity(
        "ring_dp2_kb",
        RingBigClamModel(g, ckb, mesh2),
        RingBigClamModel(g, xkb, mesh2), 12, "csr_ring_fused_kb",
    )
    # fused vs split: identical inputs, ONE step, bit-for-bit (same
    # accumulation order by construction)
    F0 = np.random.default_rng(9).uniform(0.0, 1.0, (n, 6))
    m_split = BigClamModel(g, cfg(csr_fused=False))
    m_fused = BigClamModel(g, c)
    s_s = m_split._step(m_split.init_state(F0))
    s_f = m_fused._step(m_fused.init_state(F0))
    checks["fused_vs_split_first_step_bitwise"] = np.array_equal(
        np.asarray(s_s.F), np.asarray(s_f.F)
    )
    checks["no_xla_fallback_recorded"] = all(
        p != "xla" and "fused" in p for p in paths.values()
    )
    detail["paths"] = paths
    detail["parity_bands"] = band

    # --- 2. store-native: bit-identity incl. the K-blocked gap ----------
    sedges = []
    for base_ in (0, 12):
        for i in range(12):
            for j in range(i + 1, 12):
                sedges.append((base_ + i, base_ + j))
    sedges.append((11, 12))
    sg = graph_from_edges(sedges, num_nodes=24)
    text = os.path.join(work, "g.txt")
    with open(text, "w") as f:
        for u, v in sedges:
            f.write(f"{u}\t{v}\n")
    store = compile_graph_cache(
        text, os.path.join(work, "cache"), num_shards=4, chunk_bytes=64
    )
    sF0 = np.random.default_rng(5).uniform(0.1, 1.0, size=(24, 2))
    mesh4 = make_mesh((4, 1), jax.devices()[:4])
    for kb, tag in ((0, "flat"), (1, "kb")):
        sc = cfg(num_communities=2, csr_block_b=3, csr_k_block=kb)
        refm = ShardedBigClamModel(sg, sc, mesh4)
        ref = refm.fit(sF0)
        m = StoreShardedBigClamModel(store, sc, mesh4)
        got = m.fit(sF0)
        want = "csr_fused_kb" if kb else "csr_fused"
        checks[f"store_sharded_{tag}_path"] = (
            m.engaged_path == want and refm.engaged_path == want
        )
        checks[f"store_sharded_{tag}_bitident"] = (
            np.array_equal(got.F, ref.F)
            and got.llh_history == ref.llh_history
        )
        rrefm = RingBigClamModel(sg, sc, mesh4, balance=False)
        rref = rrefm.fit(sF0)
        rm = StoreRingBigClamModel(store, sc, mesh4)
        rgot = rm.fit(sF0)
        rwant = "csr_ring_fused_kb" if kb else "csr_ring_fused"
        checks[f"store_ring_{tag}_path"] = (
            rm.engaged_path == rwant and rrefm.engaged_path == rwant
        )
        checks[f"store_ring_{tag}_bitident"] = (
            np.array_equal(rgot.F, rref.F)
            and rgot.llh_history == rref.llh_history
        )

    # --- 3. sparse merge kernel: exact + bit-identical fits -------------
    from bigclam_tpu.ops.sparse_members import (
        member_lookup,
        member_lookup_pallas,
    )

    mrng = np.random.default_rng(11)
    E, M, K = 53, 8, 20
    iv = np.full((E, M), K, np.int32)
    wv = np.zeros((E, M), np.float32)
    iu = np.full((E, M), K, np.int32)
    for r in range(E):
        pick = np.sort(mrng.choice(K, size=int(mrng.integers(0, M + 1)),
                                   replace=False))
        iv[r, : pick.size] = pick
        wv[r, : pick.size] = mrng.random(pick.size).astype(np.float32)
        pick2 = np.sort(mrng.choice(K, size=int(mrng.integers(0, M + 1)),
                                    replace=False))
        iu[r, : pick2.size] = pick2
    ref_v = np.asarray(member_lookup(
        jnp.asarray(iv), jnp.asarray(wv), jnp.asarray(iu), K
    ))
    got_v = np.asarray(member_lookup_pallas(
        jnp.asarray(iv), jnp.asarray(wv), jnp.asarray(iu), K,
        interpret=True,
    ))
    checks["sparse_merge_exact_vs_searchsorted"] = np.array_equal(
        ref_v, got_v
    )
    scfg = BigClamConfig(
        num_communities=8, representation="sparse", sparse_m=4,
        dtype="float32", edge_chunk=64,
    )
    sp_F0 = np.random.default_rng(12).uniform(0.0, 1.0, (n, 8))
    m_sx = SparseBigClamModel(g, scfg.replace(sparse_pallas_merge=False))
    m_sp = SparseBigClamModel(
        g, scfg.replace(sparse_pallas_merge=True, pallas_interpret=True)
    )
    ss_x = m_sx.init_state(sp_F0)
    ss_p = m_sp.init_state(sp_F0)
    for _ in range(4):
        ss_x, ss_p = m_sx._step(ss_x), m_sp._step(ss_p)
    checks["sparse_fit_bitident_m_lt_k"] = (
        np.array_equal(np.asarray(ss_x.F), np.asarray(ss_p.F))
        and np.array_equal(np.asarray(ss_x.ids), np.asarray(ss_p.ids))
    )
    checks["sparse_merge_path_recorded"] = (
        m_sp.engaged_path == "sparse_merge_pallas"
        and m_sx.engaged_path == "sparse_xla"
    )
    m_shx = SparseShardedBigClamModel(
        g, scfg.replace(sparse_pallas_merge=False), mesh2
    )
    m_shp = SparseShardedBigClamModel(
        g, scfg.replace(sparse_pallas_merge=True, pallas_interpret=True),
        mesh2,
    )
    sh_x, sh_p = m_shx.init_state(sp_F0), m_shp.init_state(sp_F0)
    for _ in range(3):
        sh_x, sh_p = m_shx._step(sh_x), m_shp._step(sh_p)
    checks["sparse_sharded_fit_bitident"] = np.array_equal(
        np.asarray(sh_x.F), np.asarray(sh_p.F)
    )

    # --- 4. bytes model: fused <= 0.6x split at the K=128 bench point ---
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"),
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    split_b = bench.roofline_model(128)["bytes_per_edge_iter"]
    fused_b = bench.roofline_model_fused(128)["bytes_per_edge_iter"]
    checks["roofline_fused_le_0p6x_split"] = fused_b <= 0.6 * split_b
    # the memory model's dst-row transient: the K=128 bench-shaped dense
    # model (split) vs the fused re-pricing
    k128 = cfg(num_communities=128, csr_block_b=16, csr_tile_t=16)
    mm_split = BigClamModel(g, k128.replace(csr_fused=False))
    mm_fused = BigClamModel(g, k128)
    bs = mm_split.memory.buffer_bytes()
    bf = mm_fused.memory.buffer_bytes()
    fd_split = bs.get("transient/fd_gather", 0.0)
    fd_fused = bf.get("transient/fd_dma_scratch", 0.0)
    checks["memory_fd_transient_le_0p6x"] = (
        fd_split > 0 and 0 < fd_fused <= 0.6 * fd_split
        and "transient/fd_gather" not in bf
    )
    detail["bytes_model"] = {
        "roofline_split_bytes_per_edge": split_b,
        "roofline_fused_bytes_per_edge": fused_b,
        "roofline_ratio": round(fused_b / split_b, 4),
        "memory_fd_gather_split": fd_split,
        "memory_fd_dma_scratch_fused": fd_fused,
        "memory_fd_ratio": round(fd_fused / max(fd_split, 1.0), 4),
    }

    # --- 5. ledger: kernel_path refuses the cross-baseline --------------
    from bigclam_tpu.cli import main as cli_main
    from bigclam_tpu.obs import ledger as L
    from bigclam_tpu.obs.report import load_events
    from bigclam_tpu.obs.telemetry import RunTelemetry, install, uninstall
    from bigclam_tpu.utils.profiling import StageProfile

    def run_fit(tag, run_cfg):
        tdir = os.path.join(work, tag)
        t = install(RunTelemetry(tdir, entry="fit", quiet=True))
        try:
            mdl = BigClamModel(g, run_cfg)
            with StageProfile().stage("fit"):
                res = mdl.fit(
                    np.random.default_rng(7).uniform(0.0, 1.0, (n, 6))
                )
            t.set_final({
                "llh": res.llh, "iters": res.num_iters,
                "n": g.num_nodes, "edges": g.num_edges, "k": 6,
                "kernel_path": mdl.engaged_path,
                "hbm_modeled_bytes": round(mdl.memory.hbm_bytes(), 1),
            })
            rep = t.finalize()
        finally:
            uninstall(t)
        ev = load_events(tdir) or []
        secs = [e["sec_per_iter"] for e in ev
                if e.get("kind") == "step"
                and isinstance(e.get("sec_per_iter"), (int, float))]
        return L.build_record(rep, secs or [0.01] * 6)

    rec_fused = run_fit("fused", c)
    rec_split = run_fit("split", cfg(csr_fused=False))
    rec_xla = run_fit("xla", x)
    checks["ledger_records_kernel_path"] = (
        rec_fused.get("kernel_path") == "csr_fused"
        and rec_split.get("kernel_path") == "csr"
        and rec_xla.get("kernel_path") == "xla"
    )
    ledger_path = os.path.join(work, "ledger.jsonl")
    led = L.PerfLedger(ledger_path)
    led.append(rec_split)
    led.append(rec_xla)
    led.append(dict(rec_fused, run="fused-1"))
    # only split/xla baselines exist -> the fused record has NO baseline
    rc_nobase = cli_main(["perf", "diff", "--ledger", ledger_path])
    checks["perf_diff_refuses_cross_path_baseline"] = rc_nobase == 1
    led.append(dict(rec_fused, run="fused-2", ts=rec_fused["ts"] + 1))
    rc_same = cli_main(["perf", "diff", "--ledger", ledger_path])
    checks["perf_diff_passes_identical_fused"] = rc_same == 0
    detail["perf_diff"] = {
        "no_baseline_rc": rc_nobase, "identical_rc": rc_same,
    }

    ok = all(checks.values())
    artifact = {
        "gate": "kernel_r17",
        "created_unix": round(time.time(), 1),
        "pass": ok,
        "checks": checks,
        "detail": detail,
        "device": str(jax.devices()[0]),
        "jax": jax.__version__,
        "note": (
            "fused Pallas edge superstep (in-kernel dst DMA, "
            "double-buffered; ops.pallas_fused) engages on all four "
            "trainer families in interpret mode with no XLA fallback; "
            "trajectories within the LLH band of the XLA path (first "
            "step bitwise vs split); store-built fused fits (incl. the "
            "previously-refused K-blocked large-K store layout) "
            "bit-identical to in-memory; sparse member-merge kernel "
            "EXACT vs searchsorted with bit-identical M<K fits single "
            "+ sharded; modeled bytes-per-step fused <= 0.6x split at "
            "K=128 on both the roofline and memory models; "
            "kernel_path in the ledger match key refuses fused-vs-"
            "split/xla baselines (cli perf diff). Real-chip hbm_frac "
            ">= 0.6 remains with the ROADMAP item 1 pod drill."
        ),
    }
    line = json.dumps(artifact, sort_keys=True)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    if not ok:
        bad = sorted(k for k, v in checks.items() if not v)
        print(f"FAILED checks: {bad}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
