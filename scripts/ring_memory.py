"""Ring vs all-gather PEAK-MEMORY measurement + overhead decomposition
(VERDICT r4 item 2).

The ring schedule exists for its memory profile: peak per-device HBM
O(2 * N/dp * K_loc) (resident + rotating shard) vs the all-gather
schedule's O(N * K_loc) (every device materializes full F each step).
WEAKSCALING_r04 showed the ring LOSING 7.8x on step time at dp=8 on the
CPU fake without measuring the memory it buys. This script produces both
halves of the story:

1. PEAK MEMORY, from the compiler: XLA's buffer assignment
   (compiled.memory_analysis(), temp+argument bytes, PER-DEVICE) for one
   optimizer step of each schedule, dp = 1/2/4/8 at fixed per-shard size
   — the same buffer assignment XLA performs for TPU HBM; static and
   deterministic. Per-device peak = schedule-dependent F buffers
   (all-gather O(N*K_loc) vs ring O(2 * N/dp * K_loc)) + schedule-
   INDEPENDENT workspace W (live (edge_chunk, K) gather buffers from the
   scan body + candidate accumulators). The config pins W small
   (edge_chunk=1024, K=64, per_shard=65536) so the F term is visible at
   dp <= 8; the headline is the SLOPE: all-gather peak must grow ~
   linearly in dp (one per-shard-F per added shard) while ring peak
   stays flat. The measured slope/intercept then project the advantage
   at the BASELINE config-5 design point (dp=64). Both schedules also
   carry ~3 schedule-independent F-sized working copies (grad, F_new,
   candidate accumulators), so the asymptotic advantage is ~ dp/5.
   Compile-only: no step execution. Done-bar: ring flat (dp8 <= 1.5x
   dp2... dp1 has no rotation buffer), all-gather slope within 2x of
   per-shard-F theory, dp8 measured ratio >= 1.4, projected dp64 >= 6.

2. THE 7.8x RESOLVED (bucket_balance_dp8 + tiny-step sections): the
   weak-scaling graphs have CONTIGUOUS planted blocks, so ~every edge is
   shard-local; the ring's per-(shard, phase) edge buckets pad to the
   max bucket (the diagonal), and the step sweeps ~dp x the real edge
   volume — measured 4.66M padded slots vs 297K real at dp=8, 15.7x.
   On uniformly-spread edges the buckets balance and the ring times at
   PARITY with all-gather (measured 0.99x). The tiny-step probe shows
   per-phase fixed dispatch is negligible (<1% of the gap). Mitigation
   for locality-ordered real graphs: shuffle/relabel node ids (or
   balance=True) before the ring schedule — see parallel/ring.py.

    python scripts/ring_memory.py [out.json]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

MEM_PER_SHARD, MEM_K, MEM_CHUNK = 65536, 64, 1024
TIME_PER_SHARD, TIME_K = 2048, 8


def _mem_stats(model, state):
    """Compiler memory analysis for one step (whole-program bytes)."""
    fn = model._step
    jitted = getattr(fn, "jitted", None)
    if jitted is None:
        return None
    lowered = jitted.lower(state, *fn.jit_args)
    ma = lowered.compile().memory_analysis()
    if ma is None:
        return None
    return {
        "argument_bytes": int(ma.argument_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "peak_bytes": int(ma.argument_size_in_bytes + ma.temp_size_in_bytes),
    }


def _time_step(model, state, steps):
    import jax

    state = model._step(state)              # compile + warm
    jax.block_until_ready(state.F)
    t0 = time.perf_counter()
    for _ in range(steps):
        state = model._step(state)
    jax.block_until_ready(state.F)
    return (time.perf_counter() - t0) / steps


def _build(cls, cfg, per_shard, dp, k, mesh, seed, uniform=False):
    from bigclam_tpu.models.agm import sample_planted_graph

    n = per_shard * dp
    if uniform:
        # UNIFORM edge endpoints for the memory section: a planted graph
        # with contiguous blocks makes every edge shard-local, and the
        # ring's per-(shard, phase) edge buckets pad to the max bucket —
        # a dp-fold argument blowup that swamps the F story (real runs
        # hit this too: relabel/shuffle node ids for ring schedules on
        # locality-ordered graphs; see parallel/ring.py).
        from bigclam_tpu.graph.ingest import graph_from_edges

        rng = np.random.default_rng(seed)
        m = 9 * n           # avg UNDIRECTED degree ~ 17 like the planted cfg
        e = rng.integers(0, n, size=(m, 2), dtype=np.int64)
        g = graph_from_edges(e[e[:, 0] != e[:, 1]], num_nodes=n)
    else:
        g, _ = sample_planted_graph(
            n, max(n // 256, 2), p_in=0.15, rng=np.random.default_rng(seed)
        )
    F0 = np.random.default_rng(0).uniform(0.1, 1.0, size=(n, k))
    model = cls(g, cfg, mesh)
    return model, model.init_state(F0)


def run(out_path=None) -> dict:
    import jax

    from bigclam_tpu.utils.dist import request_cpu_devices

    try:
        jax.config.update("jax_platforms", "cpu")
        request_cpu_devices(8)
    except RuntimeError:
        pass
    if len(jax.devices()) < 8:
        raise RuntimeError("need 8 CPU devices (run before other jax use)")

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.parallel import (
        RingBigClamModel,
        ShardedBigClamModel,
        make_mesh,
    )

    pairs = (("allgather", ShardedBigClamModel), ("ring", RingBigClamModel))

    # --- 1. compile-only memory analysis, F-dominated sizing ---
    mem_cfg = BigClamConfig(num_communities=MEM_K, use_pallas=False,
                            use_pallas_csr=False, edge_chunk=MEM_CHUNK)
    mem = {}
    for dp in (1, 2, 4, 8):
        mesh = make_mesh((dp, 1), jax.devices()[:dp])
        row = {}
        for name, cls in pairs:
            model, state = _build(cls, mem_cfg, MEM_PER_SHARD, dp, MEM_K,
                                  mesh, seed=dp, uniform=True)
            row[name] = _mem_stats(model, state)
        f_shard = MEM_PER_SHARD * MEM_K * 4
        row["f_bytes_theory"] = {
            "full_F": f_shard * dp, "per_shard_F": f_shard,
        }
        mem[dp] = row

    # --- 3. the 7.8x resolution: balanced-bucket timing at dp=8 ---
    # WEAKSCALING_r04's planted graphs have CONTIGUOUS blocks -> ~every
    # edge is shard-local -> the ring's per-(shard, phase) buckets pad to
    # the diagonal bucket and the step sweeps ~dp x the real edge volume.
    # On uniformly-spread edges the buckets balance and the ring runs at
    # parity. Both cases recorded, with the padded edge-slot counts that
    # prove the mechanism.
    def _padded_slots(model):
        fn = model._step
        return (int(np.prod(fn.jit_args[0].shape))
                if hasattr(fn, "jit_args") else -1)

    t_cfg0 = BigClamConfig(num_communities=TIME_K, use_pallas=False,
                           use_pallas_csr=False)
    mesh8 = make_mesh((8, 1), jax.devices()[:8])
    buckets = {}
    for label, uni in (("planted_shard_local", False), ("uniform", True)):
        row = {}
        for name, cls in pairs:
            model, state = _build(cls, t_cfg0, TIME_PER_SHARD, 8, TIME_K,
                                  mesh8, seed=8, uniform=uni)
            row[name] = {
                "sec_per_step": round(_time_step(model, state, 5), 4),
                "padded_edge_slots": _padded_slots(model),
            }
        row["ring_over_allgather"] = round(
            row["ring"]["sec_per_step"] / row["allgather"]["sec_per_step"], 2
        )
        buckets[label] = row

    # --- 2. step-time + tiny-step overhead decomposition (r04 config) ---
    t_cfg = BigClamConfig(num_communities=TIME_K, use_pallas=False,
                          use_pallas_csr=False)
    step_time, tiny_time = {}, {}
    for dp in (1, 2, 4, 8):
        mesh = make_mesh((dp, 1), jax.devices()[:dp])
        rt, rtiny = {}, {}
        for name, cls in pairs:
            model, state = _build(cls, t_cfg, TIME_PER_SHARD, dp, TIME_K,
                                  mesh, seed=dp)
            rt[name] = round(_time_step(model, state, 5), 4)
            tmodel, tstate = _build(cls, t_cfg, 64, dp, TIME_K, mesh,
                                    seed=99)
            rtiny[name] = round(_time_step(tmodel, tstate, 10), 4)
        step_time[dp] = rt
        tiny_time[dp] = rtiny

    f_shard = MEM_PER_SHARD * MEM_K * 4
    ag = {dp: mem[dp]["allgather"]["peak_bytes"] for dp in (1, 2, 4, 8)}
    rg = {dp: mem[dp]["ring"]["peak_bytes"] for dp in (1, 2, 4, 8)}
    slope_ag = (ag[8] - ag[1]) / 7.0       # bytes added per extra shard
    ring_flat = rg[8] <= 1.5 * rg[2]    # dp1 has no rotation buffer
    ratio8 = ag[8] / max(rg[8], 1)
    # linear projection to the BASELINE config-5 design point: all-gather
    # adds one per-shard F per shard, ring stays at its dp=8 level
    proj64 = (ag[1] + slope_ag * 63) / max(rg[8], 1)
    t8, tiny8 = step_time[8], tiny_time[8]
    gap = t8["ring"] - t8["allgather"]
    fixed_gap = tiny8["ring"] - tiny8["allgather"]
    rec = {
        "bench": "ring-memory+overhead",
        "mem_config": f"per_shard={MEM_PER_SHARD} K={MEM_K} f32 "
                      f"edge_chunk={MEM_CHUNK}",
        "time_config": f"per_shard={TIME_PER_SHARD} K={TIME_K}",
        "mem": mem,
        "bucket_balance_dp8": buckets,
        "step_time": step_time,
        "tiny_step_time": tiny_time,
        "per_shard_F_bytes": f_shard,
        "allgather_slope_bytes_per_shard": int(slope_ag),
        "allgather_slope_over_theory": round(slope_ag / f_shard, 2),
        "ring_dp8_over_dp2": round(rg[8] / max(rg[2], 1), 2),
        "peak_mem_ratio_dp8": round(ratio8, 2),
        "projected_ratio_dp64": round(proj64, 1),
        "dp8_gap_sec": round(gap, 4),
        "dp8_fixed_cost_gap_sec": round(fixed_gap, 4),
        "dp8_gap_fixed_share": round(fixed_gap / gap, 3) if gap > 0 else None,
        # the claim, as the compiler verifies it: all-gather's peak gains
        # ~one per-shard F per added shard (slope ~ theory); ring's stays
        # flat. The RATIO at any dp is dragged by schedule-independent
        # buffers both carry (grad, F_new, candidate accumulators ~ 3
        # F-copies + edge workspace), so the asymptotic advantage is
        # ~ dp/5, not dp/2 — measured components projected to dp=64
        # (BASELINE config-5 class) must clear 6x for the ring to be
        # worth its schedule.
        "pass": bool(
            ring_flat
            and 0.5 * f_shard <= slope_ag <= 2.0 * f_shard
            and ratio8 >= 1.4
            and proj64 >= 6.0
            # the 7.8x is bucket padding, not schedule cost: balanced
            # buckets must put the ring within 1.5x of all-gather
            and buckets["uniform"]["ring_over_allgather"] <= 1.5
        ),
    }
    line = json.dumps(rec)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    return rec


if __name__ == "__main__":
    out_path = sys.argv[1] if len(sys.argv) > 1 else None
    rec = run(out_path)
    sys.exit(0 if rec["pass"] else 1)
