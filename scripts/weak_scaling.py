"""Weak-scaling harness on the CPU device fake (VERDICT round-3 item 6a).

Multi-chip hardware is not available in this environment, so the only
scaling signal is RELATIVE: grow the graph with the shard count (per-shard
node count constant) and time one compiled step of the all-gather and ring
schedules at dp = 1/2/4/8 over the 8-device CPU fake. Absolute numbers are
CPU noise (all "devices" share the host's cores — per-device compute does
NOT stay constant the way it would on real chips); what the journal
catches is collective-schedule regressions: an accidental per-phase
all-gather, a psum moved inside a scan, or edge-bucket blowup all show up
as a step-time ratio jump between rounds.

    python scripts/weak_scaling.py [per_shard_nodes] [steps] [out.json]
"""

import json
import os
import sys
import warnings

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def run(per_shard: int = 2048, steps: int = 5, out_path=None) -> dict:
    import jax

    from bigclam_tpu.utils.dist import request_cpu_devices

    try:
        jax.config.update("jax_platforms", "cpu")
        request_cpu_devices(8)
    except RuntimeError:
        pass
    if len(jax.devices()) < 8:
        raise RuntimeError("need 8 CPU devices (run before other jax use)")

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.parallel import (
        RingBigClamModel,
        ShardedBigClamModel,
        make_mesh,
    )
    from bigclam_tpu.utils.profiling import comm_hidden_fraction, step_time

    k = 8
    cfg = BigClamConfig(num_communities=k, use_pallas=False,
                        use_pallas_csr=False)
    results = {}
    for dp in (1, 2, 4, 8):
        n = per_shard * dp
        g, _ = sample_planted_graph(
            n, max(n // 256, 2), p_in=0.15, rng=np.random.default_rng(dp)
        )
        F0 = np.random.default_rng(0).uniform(0.1, 1.0, size=(n, k))
        mesh = make_mesh((dp, 1), jax.devices()[:dp])
        row = {"n": n, "directed_edges": g.num_directed_edges}
        for name, cls, bal, cfg_m in (
            ("allgather", ShardedBigClamModel, False, cfg),
            # ring = the DEFAULT build (balance=None): since round 6 the
            # balance relabeling auto-engages when the bucket-imbalance
            # heuristic fires, so on these contiguous-block fixtures this
            # column should track ring_balanced (the ISSUE 2 acceptance:
            # ring column ~= ring_balanced)
            ("ring", RingBigClamModel, None, cfg),
            # the overlap-OFF twin of the ring column: strictly serialized
            # sweep->hop rotations (cfg.ring_overlap=False). On real chips
            # ring / ring_serial is the communication-hiding win of the
            # double-buffered schedule; on the CPU fake the pair only
            # guards the plumbing (both columns should track each other).
            ("ring_serial", RingBigClamModel, None,
             cfg.replace(ring_overlap=False)),
            # explicit relabeling — the pre-round-6 "fixed" configuration,
            # kept for the ring ~= ring_balanced acceptance column
            ("ring_balanced", RingBigClamModel, True, cfg),
            # the balance=False escape hatch: the planted fixtures have
            # CONTIGUOUS blocks — the ring's bucket-padding worst case
            # (RINGMEM_r05.json: dp x padded work). This column is what
            # the pre-round-6 "ring" column measured; the journal keeps
            # it so the imbalance overhead stays visible across rounds.
            ("ring_unbalanced", RingBigClamModel, False, cfg),
        ):
            with warnings.catch_warnings():
                # mute ONLY the known bucket-imbalance warning: the
                # imbalance is deliberately measured here (the planted
                # fixture IS the pathological case); any other warning
                # must surface
                warnings.filterwarnings(
                    "ignore", message=".*ring phase buckets are imbalanced.*"
                )
                model = cls(g, cfg_m, mesh, balance=bal)
            # shared timing protocol (bench.py's overlap_report uses the
            # same helper, so the columns stay comparable)
            row[name] = round(
                step_time(model._step, model.init_state(F0), steps=steps),
                4,
            )
        row["comm_hidden_fraction"] = comm_hidden_fraction(
            row["ring"], row["ring_serial"]
        )
        results[str(dp)] = row                 # str keys: match the JSON
    cols = (
        "allgather", "ring", "ring_serial", "ring_balanced",
        "ring_unbalanced",
    )
    base = {s: results["1"][s] for s in cols}
    rec = {
        "bench": "weak-scaling-cpu-fake",
        "per_shard_nodes": per_shard,
        "k": k,
        "steps_timed": steps,
        "sec_per_step": results,
        # ideal weak scaling = 1.0 on real chips; on the shared-core CPU
        # fake expect > 1 growth — track the TREND across rounds, not the
        # absolute value
        "rel_step_time": {
            dp: {
                s: round(results[dp][s] / base[s], 2)
                for s in cols
            }
            for dp in results
        },
    }
    line = json.dumps(rec)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    return rec


if __name__ == "__main__":
    per_shard = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    out = sys.argv[3] if len(sys.argv) > 3 else None
    run(per_shard, steps, out)
